"""Event export/import: event store ↔ JSON-lines or columnar files.

Parity: ``tools/.../export/EventsToFile.scala:40-104`` (events of one
app/channel → file; the reference's DEFAULT format there is Parquet,
``EventsToFile.scala:35,94``, with JSON as the option) and
``tools/.../imprt/FileToEvents.scala:41-103`` (file → event store). The
Spark job becomes a host-side stream. Two formats:

- ``jsonl`` — one event JSON per line, the same wire format as the REST
  API (the interchange default here).
- ``columnar`` — the Parquet analog: a compressed ``.npz`` container of
  dictionary-encoded columns (ids/types/events as int32 codes + distinct
  label tables, times as float64, properties/tags as JSON text columns).
  Re-import rebuilds raw rows straight from the columns — zero
  per-event JSON parsing — so round-tripping a 10M-event store does not
  bottleneck on the JSON codec.

``pio import`` sniffs the format (npz files are zip archives).
"""

from __future__ import annotations

import datetime as _dt
import json
import sys
from typing import Optional

import numpy as np

from predictionio_tpu.data import storage
from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    validate_event,
)

BATCH = 1000
COLUMNAR_FORMAT_VERSION = 1


def _resolve(app_name: Optional[str], app_id: Optional[int],
             channel: Optional[str]):
    apps = storage.get_metadata_apps()
    if app_name is not None:
        app = apps.get_by_name(app_name)
        if app is None:
            raise ValueError(f"App {app_name} does not exist.")
    elif app_id is not None:
        app = apps.get(app_id)
        if app is None:
            raise ValueError(f"App ID {app_id} does not exist.")
    else:
        raise ValueError("one of --app-name/--appid is required")
    channel_id = None
    if channel is not None:
        match = next(
            (c for c in storage.get_metadata_channels().get_by_appid(app.id)
             if c.name == channel), None)
        if match is None:
            raise ValueError(f"Channel {channel} does not exist.")
        channel_id = match.id
    return app.id, channel_id


def export_events(output: str, app_name: Optional[str] = None,
                  app_id: Optional[int] = None,
                  channel: Optional[str] = None,
                  format: str = "jsonl") -> int:
    """Dump every event of one app/channel (EventsToFile.scala:75-88);
    ``format`` picks jsonl (default) or the columnar npz container."""
    if format not in ("jsonl", "columnar"):
        raise ValueError(f"unknown export format {format!r} "
                         "(expected jsonl or columnar)")
    aid, channel_id = _resolve(app_name, app_id, channel)
    levents = storage.get_levents()
    events = levents.find(app_id=aid, channel_id=channel_id)
    if format == "columnar":
        if hasattr(levents, "iter_raw_rows"):
            # data-plane lane: stream raw rows straight into columns,
            # no Event objects, no per-event JSON round trip
            n = _export_columnar_raw(
                output, levents.iter_raw_rows(aid, channel_id))
        else:
            n = _export_columnar(output, events)
    else:
        n = 0
        with open(output, "w", encoding="utf-8") as f:
            for e in events:
                f.write(e.to_json())
                f.write("\n")
                n += 1
    print(f"[INFO] Events are exported to {output}. ({n} events)")
    return 0


def _dict_encode(values) -> tuple:
    """list of str|None -> (codes int32 with -1 = None, labels).

    Nulls are tracked OUT-OF-BAND (a boolean mask over the input), never
    as an in-band sentinel string: only genuinely non-null values reach
    the label table, so a real value equal to any would-be sentinel
    (e.g. the literal string ``"\\0N"``) round-trips intact."""
    null = np.fromiter((v is None for v in values), dtype=bool,
                       count=len(values))
    codes = np.full(len(values), -1, dtype=np.int32)
    present = [v for v in values if v is not None]
    if present:
        labels, pcodes = np.unique(np.asarray(present, dtype=np.str_),
                                   return_inverse=True)
        codes[~null] = pcodes.astype(np.int32)
    else:
        labels = np.empty(0, dtype=np.str_)
    return codes, labels


def _dict_decode(codes: np.ndarray, labels: np.ndarray) -> list:
    if labels.size == 0:  # every value was None
        return [None] * len(codes)
    out = labels[np.maximum(codes, 0)]
    return [None if c < 0 else v for c, v in zip(codes, out.tolist())]


def _export_columnar(output: str, events) -> int:
    cols: dict = {k: [] for k in
                  ("event_ids", "events", "entity_types", "entity_ids",
                   "target_entity_types", "target_entity_ids",
                   "properties", "tags", "pr_ids")}
    event_times, creation_times = [], []
    for e in events:
        cols["event_ids"].append(e.event_id or "")
        cols["events"].append(e.event)
        cols["entity_types"].append(e.entity_type)
        cols["entity_ids"].append(e.entity_id)
        cols["target_entity_types"].append(e.target_entity_type)
        cols["target_entity_ids"].append(e.target_entity_id)
        cols["properties"].append(
            json.dumps(e.properties.fields, sort_keys=True,
                       separators=(",", ":"))
            if e.properties.fields else "")
        cols["tags"].append(json.dumps(list(e.tags)) if e.tags else "")
        cols["pr_ids"].append(e.pr_id)
        event_times.append(e.event_time.timestamp())
        creation_times.append(e.creation_time.timestamp()
                              if e.creation_time else np.nan)
    n = len(cols["events"])
    arrays: dict = {
        "format_version": np.int64(COLUMNAR_FORMAT_VERSION),
        "n_events": np.int64(n),
        "event_ids": np.asarray(cols["event_ids"], dtype=np.str_),
        "event_times": np.asarray(event_times, dtype=np.float64),
        "creation_times": np.asarray(creation_times, dtype=np.float64),
        "properties": np.asarray(cols["properties"], dtype=np.str_),
        "tags": np.asarray(cols["tags"], dtype=np.str_),
    }
    for name in ("events", "entity_types", "entity_ids",
                 "target_entity_types", "target_entity_ids", "pr_ids"):
        codes, labels = _dict_encode(cols[name])
        arrays[f"{name}_codes"] = codes
        arrays[f"{name}_labels"] = labels
    with open(output, "wb") as f:
        np.savez_compressed(f, **arrays)
    return n


def _export_columnar_raw(output: str, raw_rows) -> int:
    """Columnar export from ``iter_raw_rows`` tuples (the
    ``insert_raw_batch`` shape) — zero Event construction."""
    rows = list(raw_rows)
    n = len(rows)

    def col(i):
        return [r[i] for r in rows]

    arrays: dict = {
        "format_version": np.int64(COLUMNAR_FORMAT_VERSION),
        "n_events": np.int64(n),
        "event_ids": np.asarray([r[0] or "" for r in rows],
                                dtype=np.str_),
        "event_times": np.asarray([float(r[7]) for r in rows],
                                  dtype=np.float64),
        "creation_times": np.asarray(
            [float(r[10]) if r[10] is not None else np.nan
             for r in rows], dtype=np.float64),
        "properties": np.asarray(
            [("" if (r[6] is None or r[6] == "{}") else r[6])
             for r in rows], dtype=np.str_),
        "tags": np.asarray(
            [("" if (r[8] is None or r[8] == "[]") else r[8])
             for r in rows], dtype=np.str_),
    }
    for name, i in (("events", 1), ("entity_types", 2),
                    ("entity_ids", 3), ("target_entity_types", 4),
                    ("target_entity_ids", 5), ("pr_ids", 9)):
        codes, labels = _dict_encode(col(i))
        arrays[f"{name}_codes"] = codes
        arrays[f"{name}_labels"] = labels
    with open(output, "wb") as f:
        np.savez_compressed(f, **arrays)
    return n


def is_columnar_export(path: str) -> bool:
    """npz containers are zip archives — sniff the magic."""
    with open(path, "rb") as f:
        return f.read(2) == b"PK"


def _import_columnar(input_path: str, levents, aid: int,
                     channel_id: Optional[int]) -> int:
    """Rebuild events from the columnar container — no per-event JSON
    parsing. Backends with the raw-row fast lane take tuples directly;
    others get typed Events (validation still applies either way: the
    exporter only writes store-validated events, but a hand-built file
    must not bypass the rules)."""
    import os as _os

    try:
        z = np.load(input_path, allow_pickle=False)
        ver = int(z["format_version"])
        if ver != COLUMNAR_FORMAT_VERSION:
            print(f"[ERROR] unsupported columnar export version {ver}",
                  file=sys.stderr)
            return 1
        n = int(z["n_events"])
        dec = {name: _dict_decode(z[f"{name}_codes"],
                                  z[f"{name}_labels"])
               for name in ("events", "entity_types", "entity_ids",
                            "target_entity_types", "target_entity_ids",
                            "pr_ids")}
        event_ids = z["event_ids"].tolist()
        props = z["properties"].tolist()
        tags = z["tags"].tolist()
        ets = z["event_times"]
        cts = z["creation_times"]
        if not all(len(c) == n for c in
                   (event_ids, props, tags, ets, cts,
                    *dec.values())):
            raise ValueError("column lengths disagree with n_events")
    except Exception as e:
        # any malformed container (zip-but-not-npz, missing arrays,
        # short columns) follows the import error contract
        print(f"[ERROR] {input_path}: not a readable columnar event "
              f"export ({e}) (nothing imported)", file=sys.stderr)
        return 1
    now_ts = _dt.datetime.now(tz=_dt.timezone.utc).timestamp()

    # validate without building Event objects (same rules as
    # validate_event; field-level, vectorized where possible)
    from predictionio_tpu.data.event import (
        BUILTIN_ENTITY_TYPES, is_reserved_prefix, is_special_event,
    )

    def err(i: int, msg: str) -> int:
        print(f"[ERROR] {input_path}[{i}]: {msg} (nothing imported)",
              file=sys.stderr)
        return 1

    for i in range(n):
        ev, etype, eid = dec["events"][i], dec["entity_types"][i], \
            dec["entity_ids"][i]
        tet, tei = dec["target_entity_types"][i], \
            dec["target_entity_ids"][i]
        if not ev:
            return err(i, "event must not be empty.")
        if not etype:
            return err(i, "entityType must not be empty string.")
        if not eid:
            return err(i, "entityId must not be empty string.")
        if tet == "":
            return err(i, "targetEntityType must not be empty string")
        if tei == "":
            return err(i, "targetEntityId must not be empty string.")
        if (tet is None) != (tei is None):
            return err(i, "targetEntityType and targetEntityId must be "
                          "specified together.")
        if ev == "$unset" and (not props[i] or props[i] == "{}"):
            return err(i, "properties cannot be empty for $unset event")
        if is_reserved_prefix(ev) and not is_special_event(ev):
            return err(i, f"{ev} is not a supported reserved event name.")
        if is_special_event(ev) and tet is not None:
            return err(i, f"Reserved event {ev} cannot have targetEntity")
        if is_reserved_prefix(etype) \
                and etype not in BUILTIN_ENTITY_TYPES:
            return err(i, f"The entityType {etype} is not allowed. "
                          "'pio_' is a reserved name prefix.")
        if tet is not None and is_reserved_prefix(tet) \
                and tet not in BUILTIN_ENTITY_TYPES:
            return err(i, f"The targetEntityType {tet} is not allowed. "
                          "'pio_' is a reserved name prefix.")
        if not np.isfinite(ets[i]):
            return err(i, "eventTime is not a finite timestamp.")
        # the raw lane writes these strings VERBATIM into the store —
        # malformed JSON would poison every later read of the app
        if props[i]:
            try:
                pf = json.loads(props[i])
                if not isinstance(pf, dict):
                    raise ValueError("properties must be a JSON object")
            except ValueError as e:
                return err(i, f"bad properties JSON: {e}")
            for key in pf:
                if is_reserved_prefix(key):
                    return err(i, f"The property {key} is not allowed. "
                                  "'pio_' is a reserved name prefix.")
        if tags[i]:
            try:
                tg = json.loads(tags[i])
                if not isinstance(tg, list):
                    raise ValueError("tags must be a JSON array")
            except ValueError as e:
                return err(i, f"bad tags JSON: {e}")

    levents.init(aid, channel_id)
    id_hex = _os.urandom(16 * max(n, 1)).hex()
    if hasattr(levents, "insert_raw_batch"):
        rows = [
            (event_ids[i] or id_hex[i * 32:i * 32 + 32],
             dec["events"][i], dec["entity_types"][i],
             dec["entity_ids"][i], dec["target_entity_types"][i],
             dec["target_entity_ids"][i], props[i] or "{}",
             float(ets[i]), tags[i] or "[]", dec["pr_ids"][i],
             float(cts[i]) if np.isfinite(cts[i]) else now_ts)
            for i in range(n)
        ]
        for i in range(0, len(rows), 20000):
            levents.insert_raw_batch(rows[i:i + 20000], aid, channel_id)
    else:
        utc = _dt.timezone.utc
        events = [
            Event(
                event=dec["events"][i],
                entity_type=dec["entity_types"][i],
                entity_id=dec["entity_ids"][i],
                target_entity_type=dec["target_entity_types"][i],
                target_entity_id=dec["target_entity_ids"][i],
                properties=json.loads(props[i]) if props[i] else {},
                event_time=_dt.datetime.fromtimestamp(float(ets[i]), utc),
                tags=tuple(json.loads(tags[i])) if tags[i] else (),
                pr_id=dec["pr_ids"][i],
                creation_time=_dt.datetime.fromtimestamp(
                    float(cts[i]), utc) if np.isfinite(cts[i]) else None,
                event_id=event_ids[i] or id_hex[i * 32:i * 32 + 32],
            )
            for i in range(n)
        ]
        for i in range(0, len(events), BATCH):
            levents.insert_batch(events[i:i + BATCH], aid, channel_id)
    print(f"[INFO] Events are imported. ({n} events)")
    return 0


def import_events(input_path: str, app_name: Optional[str] = None,
                  app_id: Optional[int] = None,
                  channel: Optional[str] = None) -> int:
    """Load a JSON-lines event file into the store
    (FileToEvents.scala:85-103).

    Uses the native C++ codec when available and the target backend
    exposes the raw-row fast lane; otherwise the pure-python path. Both
    parse + validate the WHOLE file before touching the store, so a bad
    line aborts with nothing inserted (no silent partial import).
    """
    aid, channel_id = _resolve(app_name, app_id, channel)
    levents = storage.get_levents()
    if is_columnar_export(input_path):
        return _import_columnar(input_path, levents, aid, channel_id)
    if hasattr(levents, "insert_raw_batch"):
        rc = _import_native(input_path, levents, aid, channel_id)
        if rc is not None:
            return rc
    # pure-python path (memory backend, native lib unavailable, ...)
    events = []
    with open(input_path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                event = Event.from_json(line)
                validate_event(event)
            except EventValidationError as e:
                print(f"[ERROR] {input_path}:{lineno}: {e} "
                      "(nothing imported)", file=sys.stderr)
                return 1
            events.append(event)
    levents.init(aid, channel_id)
    n = 0
    for i in range(0, len(events), BATCH):
        chunk = events[i:i + BATCH]
        levents.insert_batch(chunk, aid, channel_id)
        n += len(chunk)
    print(f"[INFO] Events are imported. ({n} events)")
    return 0


def _import_native(input_path: str, levents, aid: int,
                   channel_id: Optional[int]) -> Optional[int]:
    """Native-codec import: C++ parses/decodes the file in one pass; rows
    it could not express 1:1 with python semantics are re-parsed here with
    the Event oracle. Returns None if the native lib is unavailable
    (caller falls through to the python path)."""
    import math

    import os as _os

    from predictionio_tpu.data.event import (
        BUILTIN_ENTITY_TYPES, _parse_time, is_reserved_prefix,
        is_special_event,
    )
    from predictionio_tpu.native import codec

    with open(input_path, "rb") as f:
        data = f.read()
    parsed = codec.parse_jsonl(data)
    if parsed is None:
        return None

    now_ts = _dt.datetime.now(tz=_dt.timezone.utc).timestamp()
    rows = []
    fallback_events = []
    # batched event-id generation (same entropy as new_event_id's uuid4,
    # ~10x cheaper at bulk scale)
    id_hex = _os.urandom(16 * len(parsed)).hex()

    def err(i: int, msg: str) -> int:
        print(f"[ERROR] {input_path}:{int(parsed.lineno[i])}: {msg} "
              "(nothing imported)", file=sys.stderr)
        return 1

    for i in range(len(parsed)):
        flags = int(parsed.flags[i])
        if flags & codec.FALLBACK:
            raw = data[parsed.line_start[i]:parsed.line_end[i]] \
                .decode("utf-8", errors="replace").strip()
            try:
                event = Event.from_json(raw)
                validate_event(event)
            except EventValidationError as e:
                return err(i, str(e))
            fallback_events.append(event)
            continue
        ev = parsed.event[i]
        etype = parsed.entity_type[i]
        eid = parsed.entity_id[i]
        tet = parsed.target_entity_type[i]
        tei = parsed.target_entity_id[i]
        # validation 1:1 with validate_event (data/event.py:163-208)
        if not ev:
            return err(i, "event must not be empty.")
        if not etype:
            return err(i, "entityType must not be empty string.")
        if not eid:
            return err(i, "entityId must not be empty string.")
        if tet == "":
            return err(i, "targetEntityType must not be empty string")
        if tei == "":
            return err(i, "targetEntityId must not be empty string.")
        if (tet is None) != (tei is None):
            return err(i, "targetEntityType and targetEntityId must be "
                          "specified together.")
        # PROPS_EMPTY is set by the codec only when a properties key was
        # present; a fully absent properties field is equally empty
        if ev == "$unset" and (flags & codec.PROPS_EMPTY
                               or parsed.properties_json[i] is None):
            return err(i, "properties cannot be empty for $unset event")
        if is_reserved_prefix(ev) and not is_special_event(ev):
            return err(i, f"{ev} is not a supported reserved event name.")
        if is_special_event(ev) and tet is not None:
            return err(i, f"Reserved event {ev} cannot have targetEntity")
        if is_reserved_prefix(etype) and etype not in BUILTIN_ENTITY_TYPES:
            return err(i, f"The entityType {etype} is not allowed. "
                          "'pio_' is a reserved name prefix.")
        if tet is not None and is_reserved_prefix(tet) \
                and tet not in BUILTIN_ENTITY_TYPES:
            return err(i, f"The targetEntityType {tet} is not allowed. "
                          "'pio_' is a reserved name prefix.")
        if flags & codec.BAD_PROP_KEY:
            return err(i, f"The property {parsed.bad_prop_key[i]} is not "
                          "allowed. 'pio_' is a reserved name prefix.")
        et = parsed.event_time[i]
        if math.isnan(et):
            raw_t = parsed.event_time_raw[i]
            if raw_t is None:
                et = now_ts
            else:
                try:
                    et = _parse_time(raw_t).timestamp()
                except EventValidationError as e:
                    return err(i, str(e))
        ct = parsed.creation_time[i]
        if math.isnan(ct):
            raw_t = parsed.creation_time_raw[i]
            if raw_t is None:
                ct = now_ts
            else:
                try:
                    ct = _parse_time(raw_t).timestamp()
                except EventValidationError as e:
                    return err(i, str(e))
        rows.append((parsed.event_id[i] or id_hex[i * 32:i * 32 + 32],
                     ev, etype, eid, tet, tei,
                     parsed.properties_json[i] or "{}", et,
                     parsed.tags_json[i] or "[]", parsed.pr_id[i], ct))

    levents.init(aid, channel_id)
    for i in range(0, len(rows), 20000):
        levents.insert_raw_batch(rows[i:i + 20000], aid, channel_id)
    for i in range(0, len(fallback_events), BATCH):
        levents.insert_batch(fallback_events[i:i + BATCH], aid, channel_id)
    n = len(rows) + len(fallback_events)
    print(f"[INFO] Events are imported. ({n} events)")
    return 0


def dispatch_export(args) -> int:
    try:
        return export_events(args.output, app_name=args.app_name,
                             app_id=args.appid, channel=args.channel,
                             format=getattr(args, "format", "jsonl"))
    except ValueError as e:
        print(f"[ERROR] {e}", file=sys.stderr)
        return 1


def dispatch_import(args) -> int:
    try:
        return import_events(args.input, app_name=args.app_name,
                             app_id=args.appid, channel=args.channel)
    except ValueError as e:
        print(f"[ERROR] {e}", file=sys.stderr)
        return 1
