"""Event export/import: event store ↔ JSON-lines files.

Parity: ``tools/.../export/EventsToFile.scala:40-104`` (events of one
app/channel → file of JSON events) and ``tools/.../imprt/FileToEvents.scala
:41-103`` (file → event store). The Spark job becomes a host-side stream;
the wire format is the same per-line event JSON the REST API uses.
"""

from __future__ import annotations

import json
import sys
from typing import Optional

from predictionio_tpu.data import storage
from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    validate_event,
)

BATCH = 1000


def _resolve(app_name: Optional[str], app_id: Optional[int],
             channel: Optional[str]):
    apps = storage.get_metadata_apps()
    if app_name is not None:
        app = apps.get_by_name(app_name)
        if app is None:
            raise ValueError(f"App {app_name} does not exist.")
    elif app_id is not None:
        app = apps.get(app_id)
        if app is None:
            raise ValueError(f"App ID {app_id} does not exist.")
    else:
        raise ValueError("one of --app-name/--appid is required")
    channel_id = None
    if channel is not None:
        match = next(
            (c for c in storage.get_metadata_channels().get_by_appid(app.id)
             if c.name == channel), None)
        if match is None:
            raise ValueError(f"Channel {channel} does not exist.")
        channel_id = match.id
    return app.id, channel_id


def export_events(output: str, app_name: Optional[str] = None,
                  app_id: Optional[int] = None,
                  channel: Optional[str] = None) -> int:
    """Dump every event of one app/channel as JSON lines
    (EventsToFile.scala:75-88)."""
    aid, channel_id = _resolve(app_name, app_id, channel)
    n = 0
    with open(output, "w", encoding="utf-8") as f:
        for e in storage.get_levents().find(app_id=aid,
                                            channel_id=channel_id):
            f.write(e.to_json())
            f.write("\n")
            n += 1
    print(f"[INFO] Events are exported to {output}. ({n} events)")
    return 0


def import_events(input_path: str, app_name: Optional[str] = None,
                  app_id: Optional[int] = None,
                  channel: Optional[str] = None) -> int:
    """Load a JSON-lines event file into the store
    (FileToEvents.scala:85-103)."""
    aid, channel_id = _resolve(app_name, app_id, channel)
    # Parse + validate the WHOLE file before touching the store, so a bad
    # line aborts with nothing inserted (no silent partial import).
    events = []
    with open(input_path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                event = Event.from_json(line)
                validate_event(event)
            except EventValidationError as e:
                print(f"[ERROR] {input_path}:{lineno}: {e} "
                      "(nothing imported)", file=sys.stderr)
                return 1
            events.append(event)
    levents = storage.get_levents()
    levents.init(aid, channel_id)
    n = 0
    for i in range(0, len(events), BATCH):
        chunk = events[i:i + BATCH]
        levents.insert_batch(chunk, aid, channel_id)
        n += len(chunk)
    print(f"[INFO] Events are imported. ({n} events)")
    return 0


def dispatch_export(args) -> int:
    try:
        return export_events(args.output, app_name=args.app_name,
                             app_id=args.appid, channel=args.channel)
    except ValueError as e:
        print(f"[ERROR] {e}", file=sys.stderr)
        return 1


def dispatch_import(args) -> int:
    try:
        return import_events(args.input, app_name=args.app_name,
                             app_id=args.appid, channel=args.channel)
    except ValueError as e:
        print(f"[ERROR] {e}", file=sys.stderr)
        return 1
