"""``pio`` console — operator CLI.

Parity target: ``tools/.../console/Console.scala:133-769``. Verbs:
version, status, build, train, eval, deploy, undeploy, eventserver,
adminserver, dashboard, app (incl. channels), accesskey, template,
export, import.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from predictionio_tpu import __version__


def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_status(args) -> int:
    """Verify storage wiring (Console status -> Storage.verifyAllDataObjects,
    Storage.scala:335-358). With ``--fleet URL``, also scrape a running
    balancer's federated ``/stats.json`` and print member health + SLO
    alerts."""
    from predictionio_tpu.data import storage
    from predictionio_tpu.data.storage.base import StorageError

    try:
        cfg = storage.registry().config
        print("[INFO] Storage sources:")
        for name, src in cfg.sources.items():
            shown = {k: v for k, v in src.items()}
            print(f"[INFO]   {name}: {shown}")
        print("[INFO] Repository bindings:")
        for repo, src in cfg.repositories.items():
            print(f"[INFO]   {repo} -> {src}")
        storage.verify_all_data_objects()
        _print_fleet_health(storage)
    except StorageError as e:
        print(f"[ERROR] Storage check failed: {e}", file=sys.stderr)
        return 1
    fleet_url = getattr(args, "fleet", None)
    if fleet_url:
        if _print_balancer_status(fleet_url) != 0:
            return 1
    print("[INFO] Your system is all ready to go.")
    return 0


def _print_balancer_status(url: str) -> int:
    """Federated fleet summary off a balancer's ``/stats.json``
    (``pio status --fleet URL``)."""
    from predictionio_tpu.tools import top_command

    try:
        stats = top_command._fetch(url.rstrip("/") + "/stats.json")
    except Exception as e:
        print(f"[ERROR] Fleet balancer {url} unreachable: {e}",
              file=sys.stderr)
        return 1
    fleet = stats.get("fleet") or {}
    members = fleet.get("members") or []
    scrape = fleet.get("scrape") or {}
    print(f"[INFO] Query fleet: {fleet.get('readyReplicas', 0)}/"
          f"{len(fleet.get('replicas') or [])} replicas ready, "
          f"{len(members)} observability members "
          f"(scrape {float(scrape.get('durationSec') or 0) * 1e3:.1f}ms, "
          f"{len(scrape.get('problems') or [])} problems)")
    for m in members:
        state = "ok" if m.get("ok") else (m.get("reason") or "down")
        if m.get("inProcess"):
            state += ", in-process"
        print(f"[INFO]   member {m.get('member', '?')}: "
              f"{m.get('url') or 'local'} [{state}]")
    alerts = stats.get("alerts") or {}
    firing = alerts.get("firing") or []
    if firing:
        print(f"[WARN] SLO alerts FIRING: {', '.join(firing)}")
        for name in firing:
            obj = (alerts.get("objectives") or {}).get(name) or {}
            burn = obj.get("burn") or {}
            print(f"[WARN]   {name}: burn fast {burn.get('fast')} / "
                  f"slow {burn.get('slow')} (threshold "
                  f"{alerts.get('burnThreshold')}), since "
                  f"{obj.get('since', '?')}")
    else:
        print("[INFO] SLO alerts: none firing")
    return 0


def _print_fleet_health(storage) -> None:
    """When EVENTDATA is the sharded ``fleet`` source, print per-shard
    health (the same per-URL breaker states the wire feeds)."""
    try:
        dao = storage.get_levents()
    except Exception:
        return
    topo = getattr(dao, "topology", None)
    if not callable(topo):
        return
    t = topo()
    healthy = t.get("healthyShards", 0)
    shards = t.get("shards", [])
    print(f"[INFO] Event-store fleet: {healthy}/{len(shards)} shards "
          f"healthy ({t.get('virtualNodes')} virtual nodes/shard, "
          f"{t.get('partialReads', 0)} partial reads served)")
    for s in shards:
        state = "ok" if s.get("healthy") else "DOWN"
        print(f"[INFO]   shard {s['index']}: {s['url']} "
              f"[{state}, breaker {s.get('breakerState')}]")


def cmd_app(args) -> int:
    from predictionio_tpu.tools import app_commands

    return app_commands.dispatch(args)


def cmd_accesskey(args) -> int:
    from predictionio_tpu.tools import accesskey_commands

    return accesskey_commands.dispatch(args)


def cmd_template(args) -> int:
    from predictionio_tpu.tools import template_commands

    return template_commands.dispatch(args)


def _add_engine_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--engine-variant", default="engine.json",
                   help="path to the engine variant JSON")
    p.add_argument("--engine-factory", default=None,
                   help="module:callable (overrides engine.json)")
    p.add_argument("--engine-id", default=None)
    p.add_argument("--engine-version", default=None)


def _add_metrics_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--metrics", choices=("on", "off"), default=None,
                   help="process-wide metrics instrumentation (default on; "
                        "env PIO_METRICS=0 also disables). GET /metrics "
                        "serves the Prometheus exposition either way — "
                        "off just freezes the counters")


def _add_tracing_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--tracing", choices=("on", "off"), default=None,
                   help="structured span tracing (default on; env "
                        "PIO_TRACING=0 also disables). Traces surface at "
                        "GET /traces.json and via `pio trace`")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="additionally export every retained trace as "
                        "JSONL (+ slow-queries.log) under DIR; defaults "
                        "to $PIO_TRACE_DIR when set")


def _add_serve_precision_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--serve-precision", choices=("fp32", "bf16", "int8"),
                   default=None,
                   help="serving factor-store precision (env "
                        "PIO_SERVE_PRECISION; device stores default to "
                        "bf16 on accelerators, fp32 on CPU). bf16 "
                        "halves the model's HBM and scoring traffic; "
                        "int8 (per-row fp32 scales, quality-gated like "
                        "bf16) quarters it. Scores always accumulate "
                        "fp32. fp32 is the opt-out; the host lane is "
                        "always fp32")
    p.add_argument("--serve-kernel", choices=("auto", "fused", "xla"),
                   default=None,
                   help="device top-k program family (env "
                        "PIO_SERVE_KERNEL): 'fused' = the one-program "
                        "Pallas gather+score+mask+top-k kernel (item "
                        "tiles stream HBM once per dispatch), 'xla' = "
                        "the gather/einsum/mask/top_k chain. auto "
                        "(default) picks fused on TPU, xla elsewhere")


def _add_distributed_args(p: argparse.ArgumentParser) -> None:
    """Multi-host topology flags (the spark-submit cluster plane analog,
    Runner.scala:92-210; see parallel/distributed.py for the launch
    recipe). Defaults = single-host degenerate case."""
    p.add_argument("--num-hosts", type=int, default=None,
                   help="total host processes in the job (default 1; "
                        "env PIO_NUM_HOSTS)")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator address (host 0); "
                        "required when --num-hosts > 1 "
                        "(env PIO_COORDINATOR)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's index, 0..num-hosts-1 "
                        "(env PIO_PROCESS_ID)")


def build_parser() -> argparse.ArgumentParser:
    from predictionio_tpu.tools import run_commands

    parser = argparse.ArgumentParser(
        prog="pio",
        description="predictionio-tpu console (reference: pio CLI)")
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version", help="print version").set_defaults(
        func=cmd_version)
    st = sub.add_parser("status", help="verify storage configuration")
    st.add_argument("--fleet", default=None, metavar="URL",
                    help="also scrape a running fleet balancer's "
                         "federated /stats.json at URL and print "
                         "member health + SLO alert state")
    st.set_defaults(func=cmd_status)

    app = sub.add_parser("app", help="manage apps")
    app_sub = app.add_subparsers(dest="app_command")
    new = app_sub.add_parser("new", help="create an app")
    new.add_argument("name")
    new.add_argument("--description", default=None)
    new.add_argument("--access-key", default=None)
    app_sub.add_parser("list", help="list apps")
    show = app_sub.add_parser("show", help="show an app")
    show.add_argument("name")
    delete = app_sub.add_parser("delete", help="delete an app")
    delete.add_argument("name")
    delete.add_argument("-f", "--force", action="store_true")
    dd = app_sub.add_parser("data-delete", help="delete an app's event data")
    dd.add_argument("name")
    dd.add_argument("--channel", default=None)
    dd.add_argument("-f", "--force", action="store_true")
    dc = app_sub.add_parser("data-cleanup",
                            help="delete events older than a cutoff time")
    dc.add_argument("name")
    dc.add_argument("--before", required=True,
                    help="ISO-8601 cutoff; events before it are deleted")
    dc.add_argument("--channel", default=None)
    dc.add_argument("-f", "--force", action="store_true")
    dtr = app_sub.add_parser("data-trim",
                             help="copy a time window of events to "
                                  "another app")
    dtr.add_argument("name", help="source app")
    dtr.add_argument("--dst", required=True, help="destination app")
    dtr.add_argument("--start", default=None, help="ISO-8601 window start")
    dtr.add_argument("--until", default=None, help="ISO-8601 window end")
    dtr.add_argument("--channel", default=None, help="source channel")
    dtr.add_argument("--dst-channel", default=None)
    cn = app_sub.add_parser("channel-new", help="create a channel")
    cn.add_argument("name")
    cn.add_argument("channel")
    cd = app_sub.add_parser("channel-delete", help="delete a channel")
    cd.add_argument("name")
    cd.add_argument("channel")
    cd.add_argument("-f", "--force", action="store_true")
    app.set_defaults(func=cmd_app)

    ak = sub.add_parser("accesskey", help="manage access keys")
    ak_sub = ak.add_subparsers(dest="accesskey_command")
    akn = ak_sub.add_parser("new", help="create an access key")
    akn.add_argument("app_name")
    akn.add_argument("key", nargs="?", default=None)
    akn.add_argument("--events", nargs="*", default=None,
                     help="allowed event names (default: all)")
    akl = ak_sub.add_parser("list", help="list access keys")
    akl.add_argument("app_name", nargs="?", default=None)
    akd = ak_sub.add_parser("delete", help="delete an access key")
    akd.add_argument("key")
    ak.set_defaults(func=cmd_accesskey)

    build = sub.add_parser("build", help="verify the engine directory")
    _add_engine_args(build)
    build.set_defaults(func=run_commands.cmd_build)

    train = sub.add_parser("train", help="train an engine instance")
    train.add_argument("--profile-dir", default=None,
                       help="write a jax.profiler trace of the train pass "
                            "here (TensorBoard/Perfetto); defaults to "
                            "$PIO_PROFILE_DIR when set")
    train.add_argument("--precision", choices=("fp32", "bf16"),
                       default=None,
                       help="ALS training precision policy (default "
                            "fp32 — bit-stable historical path; env "
                            "PIO_ALS_PRECISION). bf16 stores/gathers "
                            "factors as bfloat16 with fp32 "
                            "normal-equation accumulation and solve")
    train.add_argument("--checkpoint-every", type=int, default=None,
                       metavar="N",
                       help="crash-safe training: run the ALS iteration "
                            "scan in chunks of N iterations and write an "
                            "atomic checkpoint between chunks (env "
                            "PIO_CHECKPOINT_EVERY; byte-identical to the "
                            "default single-scan path). Requires "
                            "--checkpoint-dir")
    train.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                       help="directory for training checkpoints "
                            "(npz blob + sha256/fingerprint manifest per "
                            "step; defaults to $PIO_CHECKPOINT_DIR). "
                            "SIGTERM/SIGINT then drain within one chunk: "
                            "final checkpoint + clean exit")
    train.add_argument("--checkpoint-keep", type=int, default=None,
                       metavar="N",
                       help="checkpoints retained, oldest dropped first "
                            "(default 3; env PIO_CHECKPOINT_KEEP)")
    train.add_argument("--resume", action="store_true",
                       help="continue from the newest intact checkpoint "
                            "in --checkpoint-dir whose input fingerprint "
                            "(data layout + BiMaps + ALSParams + "
                            "solver/precision statics) matches this run "
                            "— final factors are byte-identical to an "
                            "uninterrupted run; a mismatched checkpoint "
                            "is refused loudly, torn files fall back to "
                            "the previous intact one")
    _add_engine_args(train)
    train.add_argument("--batch", default="")
    train.add_argument("--skip-sanity-check", action="store_true")
    train.add_argument("--stop-after-read", action="store_true")
    train.add_argument("--stop-after-prepare", action="store_true")
    _add_distributed_args(train)
    _add_tracing_args(train)
    train.set_defaults(func=run_commands.cmd_train)

    ev = sub.add_parser("eval", help="run an evaluation / tuning sweep")
    ev.add_argument("evaluation", nargs="?", default=None,
                    help="module:callable -> Evaluation (omit with --grid)")
    ev.add_argument("engine_params_generator", nargs="?", default=None,
                    help="module:callable -> EngineParamsGenerator")
    ev.add_argument("--batch", default="")
    ev.add_argument("--grid", default=None, metavar="GRID_JSON",
                    help="hyperparameter grid file ({base, configs, "
                         "data}): every ALSParams config trains in ONE "
                         "vmapped device program against shared "
                         "bucketed tables (sweepable: rank, lambda, "
                         "alpha; sized to the HBM budget, diverged "
                         "configs masked out) and a leaderboard "
                         "artifact is written with the winner's full "
                         "engine params")
    ev.add_argument("--grid-out", default="leaderboard.json",
                    help="leaderboard artifact path (with --grid)")
    ev.add_argument("--topk", type=int, default=10,
                    help="leaderboard metric cutoff (precision@k / "
                         "ndcg@k, with --grid)")
    ev.set_defaults(func=run_commands.cmd_eval)

    dep = sub.add_parser("deploy", help="serve a trained engine instance")
    _add_engine_args(dep)
    dep.add_argument("--engine-instance-id", default=None)
    dep.add_argument("--ip", default="0.0.0.0")
    dep.add_argument("--port", type=int, default=8000)
    dep.add_argument("--feedback", action="store_true")
    dep.add_argument("--event-server-ip", default="0.0.0.0")
    dep.add_argument("--event-server-port", type=int, default=7070)
    dep.add_argument("--accesskey", default=None)
    dep.add_argument("--server-config", default=None,
                     help="server.json with ssl cert/key for HTTPS "
                          "serving (default: $PIO_SERVER_CONFIG or "
                          "./server.json)")
    dep.add_argument("--foldin", choices=("on", "off"), default="off",
                     help="online fold-in: a background consumer tails "
                          "the event stream and patches fresh user "
                          "factors into the live device store — new "
                          "users servable in seconds, no /reload, no "
                          "retrain (forces the DeviceTopK backend; "
                          "cadence via PIO_FOLDIN_INTERVAL / "
                          "PIO_FOLDIN_COUNT)")
    dep.add_argument("--fleet", type=int, default=1, metavar="N",
                     help="query-server fleet mode: run N replicas "
                          "behind one keep-alive balancer on --port "
                          "(user-sticky hash-ring routing, rolling "
                          "warm /reload — the fleet is never cold; "
                          "replicas bind ephemeral loopback ports)")
    dep.add_argument("--slo-config", default=None, metavar="JSON|PATH",
                     help="fleet-mode SLO objectives: inline JSON or a "
                          "file path layered over the defaults and "
                          "$PIO_SLO_* env (windows, burn threshold, "
                          "per-objective budget/thresholdSec/disabled "
                          "— see README 'Fleet observability')")
    dep.add_argument("--batch-window", type=float, default=None,
                     metavar="SEC",
                     help="micro-batch budget in seconds (default "
                          "0.002; env PIO_BATCH_WINDOW): how long the "
                          "dispatcher holds a lone query hoping more "
                          "arrive to share its device dispatch; 0 "
                          "dispatches as soon as the dispatcher is "
                          "free")
    _add_metrics_arg(dep)
    _add_tracing_args(dep)
    _add_serve_precision_arg(dep)
    dep.set_defaults(func=run_commands.cmd_deploy)

    bp = sub.add_parser(
        "batchpredict",
        help="bulk offline scoring: run a query file (or every known "
             "entity) through a trained engine instance in restartable "
             "device-shaped chunks")
    _add_engine_args(bp)
    bp.add_argument("--engine-instance-id", default=None)
    bp.add_argument("--input", default=None,
                    help="JSONL query file (one query object per line, "
                         "the /queries.json wire format)")
    bp.add_argument("--output", default=None,
                    help="output directory: per-chunk shard files + "
                         "manifest.json (reruns resume from it)")
    bp.add_argument("--query-partitions", type=int, default=None,
                    help="split the queries into N balanced partitions "
                         "(default: fixed --chunk-size chunks)")
    bp.add_argument("--chunk-size", type=int, default=256,
                    help="queries per chunk (power-of-two aligned to the "
                         "serving buckets; default 256)")
    bp.add_argument("--format", choices=("jsonl", "npz"), default="jsonl",
                    help="shard format: jsonl (default) or columnar npz")
    bp.add_argument("--synthesize-app", default=None, metavar="APP",
                    help="instead of --input: one query per known entity "
                         "of APP (via the materialized aggregation)")
    bp.add_argument("--synthesize-entity-type", default="user")
    bp.add_argument("--synthesize-field", default="user",
                    help="query field receiving the entity id "
                         "(default 'user')")
    bp.add_argument("--synthesize-base", default="{}", metavar="JSON",
                    help="JSON object merged into every synthesized "
                         "query (e.g. '{\"num\": 10}')")
    bp.add_argument("--channel", default=None,
                    help="channel for --synthesize-app")
    bp.add_argument("--batch", default="")
    bp.add_argument("--smoke", action="store_true",
                    help="self-contained CPU smoke: seed + train a tiny "
                         "engine in memory, batch-predict, crash, resume "
                         "and verify — ignores the other flags")
    _add_metrics_arg(bp)
    _add_tracing_args(bp)
    _add_serve_precision_arg(bp)
    bp.set_defaults(func=run_commands.cmd_batchpredict)

    undep = sub.add_parser("undeploy", help="stop a deployed engine server")
    undep.add_argument("--ip", default="0.0.0.0")
    undep.add_argument("--port", type=int, default=8000)
    undep.set_defaults(func=run_commands.cmd_undeploy)

    es = sub.add_parser("eventserver", help="start the event server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true")
    es.add_argument(
        "--service-key", default=None, metavar="KEY",
        help="enable the /storage wire for remote resthttp storage "
             "clients (a storage credential, like a DB password; env "
             "PIO_EVENTSERVER_SERVICE_KEY)")
    es.add_argument(
        "--server-config", default=None, metavar="JSON",
        help="server.json with an ssl section (certfile/keyfile) to "
             "serve the whole event API over TLS")
    _add_metrics_arg(es)
    es.set_defaults(func=run_commands.cmd_eventserver)

    adm = sub.add_parser("adminserver", help="start the admin REST server")
    adm.add_argument("--ip", default="localhost")
    adm.add_argument("--port", type=int, default=7071)
    adm.set_defaults(func=run_commands.cmd_adminserver)

    dash = sub.add_parser("dashboard", help="start the evaluation dashboard")
    dash.add_argument("--ip", default="localhost")
    dash.add_argument("--port", type=int, default=9000)
    dash.add_argument("--server-config", default=None,
                      help="server.json with accessKey/ssl settings")
    dash.set_defaults(func=run_commands.cmd_dashboard)

    from predictionio_tpu.tools import trace_commands

    tr = sub.add_parser(
        "trace",
        help="inspect structured traces: list recent, dump one "
             "(optionally as Perfetto JSON), tail the slow-query log")
    tr_sub = tr.add_subparsers(dest="trace_command")

    def _add_trace_source(p):
        p.add_argument("--url", default=None, metavar="URL",
                       help="a live server's base URL (default "
                            f"{trace_commands.DEFAULT_URL} unless a "
                            "--trace-dir/$PIO_TRACE_DIR is available)")
        p.add_argument("--dir", default=None, metavar="DIR",
                       help="read from a --trace-dir JSONL export "
                            "instead of a live server (merges "
                            "per-process fragments; default "
                            "$PIO_TRACE_DIR)")
        p.add_argument("-n", type=int, default=20,
                       help="max entries to show (default 20)")

    trl = tr_sub.add_parser("list", help="recent retained traces")
    _add_trace_source(trl)
    trd = tr_sub.add_parser("dump", help="print one trace's span tree")
    trd.add_argument("trace_id")
    trd.add_argument("--perfetto", default=None, metavar="FILE",
                     help="write Chrome-trace-event JSON to FILE "
                          "(open at ui.perfetto.dev) instead of "
                          "printing the tree")
    _add_trace_source(trd)
    trt = tr_sub.add_parser("tail", help="the slow-query log")
    _add_trace_source(trt)
    tr.set_defaults(func=trace_commands.dispatch)

    from predictionio_tpu.tools import runs_command

    rn = sub.add_parser(
        "runs",
        help="training run histories: list recorded runs, render one "
             "run's loss curve, diff two runs (reads the append-only "
             "run logs under <checkpoint-dir>/runs/)")
    rn_sub = rn.add_subparsers(dest="runs_command")

    def _add_runs_dir(p):
        p.add_argument("--dir", default=None, metavar="DIR",
                       help="checkpoint directory holding runs/ "
                            "(default $PIO_CHECKPOINT_DIR)")

    rnl = rn_sub.add_parser("list", help="summarize recorded runs")
    _add_runs_dir(rnl)
    rnl.add_argument("-n", type=int, default=20,
                     help="max runs to show (default 20)")
    rns = rn_sub.add_parser(
        "show", help="one run's ASCII loss curve + sample table")
    rns.add_argument("run_id", help="run id (unique prefixes accepted)")
    _add_runs_dir(rns)
    rnc = rn_sub.add_parser(
        "compare", help="align two runs by step and diff their losses")
    rnc.add_argument("run_a")
    rnc.add_argument("run_b")
    _add_runs_dir(rnc)
    rn.set_defaults(func=runs_command.dispatch)

    from predictionio_tpu.tools import top_command

    top = sub.add_parser(
        "top",
        help="live terminal view of a deployed query server: QPS, "
             "p50/p99, batch fill, device-vs-host time split, HBM, "
             "breaker/degraded/fold-in state (polls /stats.json + "
             "/dispatches.json)")
    top.add_argument("--url", default=None, metavar="URL",
                     help="the query server's base URL (default "
                          f"{top_command.DEFAULT_URL})")
    top.add_argument("--interval", type=float, default=2.0, metavar="SEC",
                     help="refresh cadence in seconds (default 2)")
    top.add_argument("--once", action="store_true",
                     help="print one plain snapshot and exit "
                          "(scripts/CI; no ANSI)")
    top.add_argument("--fleet", action="store_true",
                     help="point --url at a fleet balancer: renders "
                          "the federated member table + SLO burn-rate "
                          "lines (and warns if the target serves no "
                          "fleet block)")
    top.set_defaults(func=top_command.cmd_top)

    tpl = sub.add_parser("template", help="engine template scaffolds")
    tpl_sub = tpl.add_subparsers(dest="template_command")
    tpl_sub.add_parser("list", help="list built-in templates")
    tg = tpl_sub.add_parser("get", help="scaffold an engine directory")
    tg.add_argument("name")
    tg.add_argument("directory")
    tpl.set_defaults(func=cmd_template)

    from predictionio_tpu.tools import export_import

    exp = sub.add_parser(
        "export", help="export events to a JSON-lines or columnar file")
    exp.add_argument("--output", required=True)
    exp.add_argument("--app-name", default=None)
    exp.add_argument("--appid", type=int, default=None)
    exp.add_argument("--channel", default=None)
    exp.add_argument(
        "--format", choices=("jsonl", "columnar"), default="jsonl",
        help="jsonl (wire-format interchange, default) or columnar "
             "(dictionary-encoded npz — the Parquet analog, "
             "EventsToFile.scala:35,94; import sniffs the format)")
    exp.set_defaults(func=export_import.dispatch_export)

    imp = sub.add_parser(
        "import", help="import events from a JSON-lines or columnar file")
    imp.add_argument("--input", required=True)
    imp.add_argument("--app-name", default=None)
    imp.add_argument("--appid", type=int, default=None)
    imp.add_argument("--channel", default=None)
    imp.set_defaults(func=export_import.dispatch_import)

    return parser


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 2
    return args.func(args)


if __name__ == "__main__":
    raise SystemExit(main())
