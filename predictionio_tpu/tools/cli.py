"""``pio`` console — operator CLI.

Parity target: ``tools/.../console/Console.scala:133-769`` (~30 verbs).
This module grows verb-by-verb; currently: status, version, app.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from predictionio_tpu import __version__


def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_status(args) -> int:
    """Verify storage wiring (Console status -> Storage.verifyAllDataObjects,
    Storage.scala:335-358)."""
    from predictionio_tpu.data import storage
    from predictionio_tpu.data.storage.base import StorageError

    try:
        cfg = storage.registry().config
        print("[INFO] Storage sources:")
        for name, src in cfg.sources.items():
            shown = {k: v for k, v in src.items()}
            print(f"[INFO]   {name}: {shown}")
        print("[INFO] Repository bindings:")
        for repo, src in cfg.repositories.items():
            print(f"[INFO]   {repo} -> {src}")
        storage.verify_all_data_objects()
    except StorageError as e:
        print(f"[ERROR] Storage check failed: {e}", file=sys.stderr)
        return 1
    print("[INFO] Your system is all ready to go.")
    return 0


def cmd_app(args) -> int:
    from predictionio_tpu.tools import app_commands

    return app_commands.dispatch(args)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pio",
        description="predictionio-tpu console (reference: pio CLI)")
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version", help="print version").set_defaults(
        func=cmd_version)
    sub.add_parser("status", help="verify storage configuration").set_defaults(
        func=cmd_status)

    app = sub.add_parser("app", help="manage apps")
    app_sub = app.add_subparsers(dest="app_command")
    new = app_sub.add_parser("new", help="create an app")
    new.add_argument("name")
    new.add_argument("--description", default=None)
    new.add_argument("--access-key", default=None)
    app_sub.add_parser("list", help="list apps")
    show = app_sub.add_parser("show", help="show an app")
    show.add_argument("name")
    delete = app_sub.add_parser("delete", help="delete an app")
    delete.add_argument("name")
    delete.add_argument("-f", "--force", action="store_true")
    dd = app_sub.add_parser("data-delete", help="delete an app's event data")
    dd.add_argument("name")
    dd.add_argument("--channel", default=None)
    dd.add_argument("-f", "--force", action="store_true")
    app.set_defaults(func=cmd_app)
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 2
    return args.func(args)


if __name__ == "__main__":
    raise SystemExit(main())
