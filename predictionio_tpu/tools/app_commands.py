"""``pio app`` subcommands: new/list/show/delete/data-delete.

Parity: ``tools/.../console/App.scala`` — creates the app with a default
access key, lists with keys, data-delete wipes one channel or the whole
event store for the app.
"""

from __future__ import annotations

import sys

from predictionio_tpu.data import storage
from predictionio_tpu.data.storage.base import AccessKey, App


def dispatch(args) -> int:
    cmd = getattr(args, "app_command", None)
    if cmd == "new":
        return app_new(args.name, args.description, args.access_key)
    if cmd == "list":
        return app_list()
    if cmd == "show":
        return app_show(args.name)
    if cmd == "delete":
        return app_delete(args.name, args.force)
    if cmd == "data-delete":
        return app_data_delete(args.name, args.channel, args.force)
    if cmd == "data-cleanup":
        return app_data_cleanup(args.name, args.before, args.channel,
                                args.force)
    if cmd == "data-trim":
        return app_data_trim(args.name, args.dst, args.start, args.until,
                             args.channel, args.dst_channel)
    if cmd == "channel-new":
        return app_channel_new(args.name, args.channel)
    if cmd == "channel-delete":
        return app_channel_delete(args.name, args.channel, args.force)
    print("usage: pio app {new,list,show,delete,data-delete,data-cleanup,"
          "data-trim,channel-new,channel-delete} ...", file=sys.stderr)
    return 2


def app_new(name: str, description=None, access_key=None) -> int:
    apps = storage.get_metadata_apps()
    if apps.get_by_name(name) is not None:
        print(f"[ERROR] App {name} already exists. Aborting.",
              file=sys.stderr)
        return 1
    app_id = apps.insert(App(0, name, description))
    if app_id is None:
        print(f"[ERROR] Unable to create app {name}.", file=sys.stderr)
        return 1
    storage.get_levents().init(app_id)
    key = storage.get_metadata_access_keys().insert(
        AccessKey(access_key or "", app_id, ()))
    print("[INFO] Created a new app:")
    print(f"[INFO]         Name: {name}")
    print(f"[INFO]           ID: {app_id}")
    print(f"[INFO]   Access Key: {key}")
    return 0


def app_list() -> int:
    apps = sorted(storage.get_metadata_apps().get_all(), key=lambda a: a.name)
    keys = storage.get_metadata_access_keys()
    print(f"[INFO] {'Name':<20} | {'ID':>4} | Access Key")
    for a in apps:
        aks = keys.get_by_appid(a.id)
        first = aks[0].key if aks else ""
        print(f"[INFO] {a.name:<20} | {a.id:>4} | {first}")
    print(f"[INFO] Finished listing {len(apps)} app(s).")
    return 0


def app_show(name: str) -> int:
    app = storage.get_metadata_apps().get_by_name(name)
    if app is None:
        print(f"[ERROR] App {name} does not exist. Aborting.",
              file=sys.stderr)
        return 1
    print(f"[INFO]       App Name: {app.name}")
    print(f"[INFO]         App ID: {app.id}")
    print(f"[INFO]    Description: {app.description or ''}")
    for k in storage.get_metadata_access_keys().get_by_appid(app.id):
        events = ",".join(k.events) if k.events else "(all)"
        print(f"[INFO]     Access Key: {k.key} | {events}")
    for c in storage.get_metadata_channels().get_by_appid(app.id):
        print(f"[INFO]        Channel: {c.name} ({c.id})")
    return 0


def delete_app_cascade(app_id: int, reg=None) -> None:
    """Remove an app and everything attached to it: per-channel event
    stores, channel rows, the default event store, access keys, and the
    app row (Console `app delete` semantics; shared by the admin REST
    server so the two paths cannot diverge)."""
    reg = reg or storage.registry()
    channels = reg.get_metadata_channels()
    levents = reg.get_levents()
    for c in channels.get_by_appid(app_id):
        levents.remove(app_id, c.id)
        channels.delete(c.id)
    levents.remove(app_id)
    keys = reg.get_metadata_access_keys()
    for k in keys.get_by_appid(app_id):
        keys.delete(k.key)
    reg.get_metadata_apps().delete(app_id)


def app_delete(name: str, force: bool = False) -> int:
    apps = storage.get_metadata_apps()
    app = apps.get_by_name(name)
    if app is None:
        print(f"[ERROR] App {name} does not exist. Aborting.",
              file=sys.stderr)
        return 1
    if not force and not _confirm(f"Delete app {name} and ALL its data?"):
        print("[INFO] Aborted.")
        return 0
    delete_app_cascade(app.id)
    print(f"[INFO] App successfully deleted: {name}")
    return 0


def app_data_delete(name: str, channel=None, force: bool = False) -> int:
    apps = storage.get_metadata_apps()
    app = apps.get_by_name(name)
    if app is None:
        print(f"[ERROR] App {name} does not exist. Aborting.",
              file=sys.stderr)
        return 1
    channel_id, rc = _resolve_channel(app, channel)
    if rc:
        return rc
    if not force and not _confirm(
            f"Delete all event data of app {name}"
            + (f" channel {channel}" if channel else "") + "?"):
        print("[INFO] Aborted.")
        return 0
    levents = storage.get_levents()
    levents.remove(app.id, channel_id)
    levents.init(app.id, channel_id)  # wipe + reinit (App.scala data-delete)
    print(f"[INFO] Removed event data of app: {name}")
    return 0


def _resolve_channel(app, channel):
    """(channel_id, error_rc): None channel -> default channel."""
    if channel is None:
        return None, None
    match = next((c for c in storage.get_metadata_channels()
                  .get_by_appid(app.id) if c.name == channel), None)
    if match is None:
        print(f"[ERROR] Channel {channel} does not exist. Aborting.",
              file=sys.stderr)
        return None, 1
    return match.id, None


def app_data_cleanup(name: str, before: str, channel=None,
                     force: bool = False) -> int:
    """Delete events older than a cutoff time — the experimental
    cleanup-app capability (``examples/experimental/scala-cleanup-app/
    .../DataSource.scala``) as a first-class verb instead of a fake
    engine run."""
    from predictionio_tpu.data.event import _parse_time

    apps = storage.get_metadata_apps()
    app = apps.get_by_name(name)
    if app is None:
        print(f"[ERROR] App {name} does not exist. Aborting.",
              file=sys.stderr)
        return 1
    channel_id, rc = _resolve_channel(app, channel)
    if rc:
        return rc
    try:
        cutoff = _parse_time(before)
    except Exception as e:
        print(f"[ERROR] Bad --before time {before!r}: {e}", file=sys.stderr)
        return 1
    if cutoff is None:
        print("[ERROR] --before time is required.", file=sys.stderr)
        return 1
    if not force and not _confirm(
            f"Delete all events of app {name} before {cutoff.isoformat()}?"):
        print("[INFO] Aborted.")
        return 0
    # no pre-count scan: at 10M+ events a typed full scan would cost more
    # than the cleanup itself; delete_until reports what it removed
    removed = storage.get_levents().delete_until(app.id, cutoff, channel_id)
    print(f"[INFO] Removed {removed} events before {cutoff.isoformat()}.")
    return 0


def app_data_trim(src: str, dst: str, start=None, until=None,
                  src_channel=None, dst_channel=None) -> int:
    """Copy a time window of events from one app to another — the
    experimental trim-app capability (``examples/experimental/
    scala-parallel-trim-app/.../DataSource.scala``: src window ->
    dst app, event IDs preserved)."""
    from predictionio_tpu.data.event import _parse_time

    apps = storage.get_metadata_apps()
    src_app = apps.get_by_name(src)
    dst_app = apps.get_by_name(dst)
    for label, app in (("Source", src_app), ("Destination", dst_app)):
        if app is None:
            print(f"[ERROR] {label} app does not exist. Aborting.",
                  file=sys.stderr)
            return 1
    src_cid, rc = _resolve_channel(src_app, src_channel)
    if rc:
        return rc
    dst_cid, rc = _resolve_channel(dst_app, dst_channel)
    if rc:
        return rc
    try:
        start_t = _parse_time(start) if start else None
        until_t = _parse_time(until) if until else None
    except Exception as e:
        print(f"[ERROR] Bad time bound: {e}", file=sys.stderr)
        return 1
    from itertools import islice

    levents = storage.get_levents()
    levents.init(dst_app.id, dst_cid)
    # idempotent re-runs: events keep their IDs, and append-only backends
    # (jsonlfs) would otherwise duplicate them on a retry
    existing = {e.event_id for e in levents.find(app_id=dst_app.id,
                                                 channel_id=dst_cid)}
    # insert in bounded chunks (read-side memory depends on the
    # backend's find(): sqlite streams, jsonlfs materializes the
    # time-ordered window)
    it = iter(levents.find(app_id=src_app.id, channel_id=src_cid,
                           start_time=start_t, until_time=until_t))
    BATCH = 5000
    copied = skipped = 0
    while True:
        chunk = [e for e in islice(it, BATCH)]
        if not chunk:
            break
        fresh = []
        for e in chunk:
            # `existing` also absorbs ids copied THIS run, so duplicate
            # ids inside the source window copy exactly once
            if e.event_id not in existing:
                existing.add(e.event_id)
                fresh.append(e)
        skipped += len(chunk) - len(fresh)
        if fresh:
            levents.insert_batch(fresh, dst_app.id, dst_cid)
            copied += len(fresh)
    msg = f"[INFO] Copied {copied} events from app {src} to {dst}."
    if skipped:
        msg += f" ({skipped} already present, skipped)"
    print(msg)
    return 0


def app_channel_new(name: str, channel: str) -> int:
    """App.scala channelNew: validate name, create channel, init its event
    store; roll back the channel row if init fails."""
    from predictionio_tpu.data.storage.base import Channel

    app = storage.get_metadata_apps().get_by_name(name)
    if app is None:
        print(f"[ERROR] App {name} does not exist. Aborting.",
              file=sys.stderr)
        return 1
    channels = storage.get_metadata_channels()
    if any(c.name == channel for c in channels.get_by_appid(app.id)):
        print(f"[ERROR] Channel {channel} already exists. Aborting.",
              file=sys.stderr)
        return 1
    if not Channel.is_valid_name(channel):
        print(f"[ERROR] Channel name {channel} is invalid (1-16 "
              "alphanumeric/dash characters). Aborting.", file=sys.stderr)
        return 1
    channel_id = channels.insert(Channel(id=0, name=channel, appid=app.id))
    if channel_id is None:
        print("[ERROR] Unable to create channel.", file=sys.stderr)
        return 1
    if not storage.get_levents().init(app.id, channel_id):
        channels.delete(channel_id)
        print("[ERROR] Unable to initialize the channel's event store.",
              file=sys.stderr)
        return 1
    print(f"[INFO] Channel {channel} created for app {name}.")
    return 0


def app_channel_delete(name: str, channel: str, force: bool = False) -> int:
    app = storage.get_metadata_apps().get_by_name(name)
    if app is None:
        print(f"[ERROR] App {name} does not exist. Aborting.",
              file=sys.stderr)
        return 1
    channel_id, rc = _resolve_channel(app, channel)
    if rc or channel_id is None:
        return rc or 1
    if not force and not _confirm(
            f"Delete channel {channel} of app {name} and ALL its data?"):
        print("[INFO] Aborted.")
        return 0
    storage.get_levents().remove(app.id, channel_id)
    storage.get_metadata_channels().delete(channel_id)
    print(f"[INFO] Channel {channel} deleted.")
    return 0


def _confirm(prompt: str) -> bool:
    try:
        return input(f"{prompt} (y/N) ").strip().lower() == "y"
    except EOFError:
        return False
