"""``pio runs list|show|compare`` — render training run histories.

The offline reader for the append-only run logs training writes under
``<checkpoint_dir>/runs/`` (workflow/runlog.py): ``list`` summarizes
every run, ``show`` renders one run's loss curve as an ASCII chart plus
its per-chunk sample table, ``compare`` aligns two runs by step and
diffs their objectives. Pure host-side file reading — no jax import, no
live server needed, works on a directory long after the training
process is gone (the ``pio trace`` offline-dir idiom).
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional, Sequence, Tuple

from predictionio_tpu.workflow import runlog


def _resolve_dir(args) -> Optional[str]:
    d = (getattr(args, "dir", None)
         or os.environ.get("PIO_CHECKPOINT_DIR", "").strip())
    if not d:
        print("runs: no directory — pass --dir or set "
              "$PIO_CHECKPOINT_DIR", file=sys.stderr)
        return None
    if not os.path.isdir(d):
        print(f"runs: directory not found: {d}", file=sys.stderr)
        return None
    return d


def _fmt_loss(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.6g}"


def _fmt_when(ts: Optional[float]) -> str:
    if not ts:
        return "-"
    import datetime as _dt

    return _dt.datetime.fromtimestamp(ts).strftime("%Y-%m-%d %H:%M:%S")


def ascii_chart(points: Sequence[Tuple[int, float]], width: int = 60,
                height: int = 12) -> List[str]:
    """Plot (step, value) points on a ``width x height`` character
    grid: ``*`` marks samples, ``·`` fills the line between adjacent
    samples, a left gutter labels the y-extremes. Degenerates politely
    for 1 sample or a flat curve."""
    points = [(int(s), float(v)) for s, v in points]
    if not points:
        return ["(no finite loss samples)"]
    points.sort(key=lambda p: p[0])
    steps = [p[0] for p in points]
    vals = [p[1] for p in points]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or abs(hi) or 1.0
    s_lo, s_hi = steps[0], steps[-1]
    s_span = (s_hi - s_lo) or 1
    grid = [[" "] * width for _ in range(height)]

    def cell(step: int, val: float) -> Tuple[int, int]:
        x = int(round((step - s_lo) / s_span * (width - 1)))
        y = int(round((val - lo) / span * (height - 1)))
        return height - 1 - y, x

    # connect adjacent samples so sparse runs still read as a curve
    for (s0, v0), (s1, v1) in zip(points, points[1:]):
        r0, c0 = cell(s0, v0)
        r1, c1 = cell(s1, v1)
        n = max(abs(c1 - c0), abs(r1 - r0), 1)
        for t in range(n + 1):
            r = r0 + (r1 - r0) * t // n
            c = c0 + (c1 - c0) * t // n
            grid[r][c] = "·"
    for s, v in points:
        r, c = cell(s, v)
        grid[r][c] = "*"

    top, bottom = f"{hi:.5g}", f"{lo:.5g}"
    gutter = max(len(top), len(bottom))
    lines = []
    for r, row in enumerate(grid):
        label = top if r == 0 else bottom if r == height - 1 else ""
        lines.append(f"{label:>{gutter}} |" + "".join(row))
    lines.append(" " * gutter + " +" + "-" * width)
    foot = f"step {s_lo}"
    tail = f"{s_hi}"
    pad = width - len(foot) - len(tail)
    lines.append(" " * gutter + "  " + foot + " " * max(1, pad) + tail)
    return lines


def _curve_points(samples: Sequence[dict]) -> List[Tuple[int, float]]:
    out = []
    for s in samples:
        total = runlog._loss_total(s)
        if total is not None:
            out.append((int(s.get("step", 0)), total))
    return out


def cmd_list(args) -> int:
    d = _resolve_dir(args)
    if d is None:
        return 2
    runs = runlog.list_runs(d)
    if not runs:
        print(f"no training runs under {d} (run `pio train` with "
              "checkpointing + telemetry on to record one)")
        return 0
    print(f"{'RUN ID':<34} {'SAMPLES':>7} {'STEP':>9} "
          f"{'LAST LOSS':>12}  {'UPDATED':<19} CONTEXT")
    for r in runs[:int(getattr(args, "n", 20) or 20)]:
        step = "-" if r["lastStep"] is None else (
            f"{r['lastStep']}/{r['totalIterations']}"
            if r["totalIterations"] else str(r["lastStep"]))
        ctx = r.get("context") or {}
        ctx_s = " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        print(f"{r['runId']:<34} {r['samples']:>7} {step:>9} "
              f"{_fmt_loss(r['lastLoss']):>12}  "
              f"{_fmt_when(r['updatedAt']):<19} {ctx_s}")
    return 0


def _load(d: str, run_id: str) -> Optional[dict]:
    path = runlog.find_run(d, run_id)
    if path is None:
        known = ", ".join(r["runId"] for r in runlog.list_runs(d)) \
            or "(none)"
        print(f"runs: no run matching {run_id!r} under {d} "
              f"(known: {known})", file=sys.stderr)
        return None
    return runlog.read_run(path)


def cmd_show(args) -> int:
    d = _resolve_dir(args)
    if d is None:
        return 2
    run = _load(d, args.run_id)
    if run is None:
        return 2
    header = run["header"]
    samples = run["samples"]
    print(f"run {run['runId']}")
    if header.get("createdAt"):
        print(f"  created      {header['createdAt']}")
    if header.get("totalIterations") is not None:
        print(f"  iterations   {header['totalIterations']} "
              f"(checkpoint every {header.get('checkpointEvery', '?')})")
    ctx = header.get("context") or {}
    if ctx:
        print("  context      "
              + " ".join(f"{k}={v}" for k, v in sorted(ctx.items())))
    print(f"  samples      {len(samples)}")
    print()
    for line in ascii_chart(_curve_points(samples)):
        print(line)
    print()
    print(f"{'STEP':>7} {'FIT':>12} {'L2':>12} {'TOTAL':>12} "
          f"{'WALL s':>8} {'HBM MB':>8}")
    for s in samples:
        loss = s.get("loss") or {}
        fit, l2 = loss.get("fit"), loss.get("l2")
        if isinstance(fit, list):
            # grid run: show the best alive config's decomposition
            total_v = loss.get("total") or []
            best = min((t for t in total_v
                        if isinstance(t, (int, float))), default=None)
            i = total_v.index(best) if best is not None else None
            fit = None if i is None else fit[i]
            l2 = None if i is None else (loss.get("l2") or [])[i]
        hbm = s.get("hbmBytesInUse")
        print(f"{s.get('step', 0):>7} {_fmt_loss(fit):>12} "
              f"{_fmt_loss(l2):>12} "
              f"{_fmt_loss(runlog._loss_total(s)):>12} "
              f"{s.get('wallSeconds', 0):>8.3f} "
              f"{'-' if hbm is None else f'{hbm / 1e6:.1f}':>8}")
    return 0


def cmd_compare(args) -> int:
    d = _resolve_dir(args)
    if d is None:
        return 2
    run_a = _load(d, args.run_a)
    run_b = _load(d, args.run_b)
    if run_a is None or run_b is None:
        return 2
    a = dict(_curve_points(run_a["samples"]))
    b = dict(_curve_points(run_b["samples"]))
    steps = sorted(set(a) | set(b))
    if not steps:
        print("neither run has finite loss samples")
        return 0
    na, nb = run_a["runId"], run_b["runId"]
    print(f"A = {na}")
    print(f"B = {nb}")
    print()
    print(f"{'STEP':>7} {'A total':>14} {'B total':>14} "
          f"{'B - A':>14}")
    for s in steps:
        va, vb = a.get(s), b.get(s)
        delta = None if va is None or vb is None else vb - va
        print(f"{s:>7} {_fmt_loss(va):>14} {_fmt_loss(vb):>14} "
              f"{_fmt_loss(delta):>14}")
    both = [s for s in steps if s in a and s in b]
    if both:
        last = both[-1]
        d_last = b[last] - a[last]
        better = "B" if d_last < 0 else "A" if d_last > 0 else "tie"
        print()
        print(f"at step {last}: {better} "
              f"{'is lower by ' + _fmt_loss(abs(d_last)) if better != 'tie' else ''}")
    return 0


def dispatch(args) -> int:
    cmd = getattr(args, "runs_command", None)
    if cmd == "list":
        return cmd_list(args)
    if cmd == "show":
        return cmd_show(args)
    if cmd == "compare":
        return cmd_compare(args)
    print("usage: pio runs {list|show|compare} [--dir DIR]",
          file=sys.stderr)
    return 2
