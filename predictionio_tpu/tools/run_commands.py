"""``pio`` lifecycle verbs: build, train, eval, deploy, undeploy,
eventserver.

Parity: ``tools/.../console/Console.scala`` dispatch (:698-769) with the
spark-submit/Runner layer removed — train/eval/deploy run in this host
process (SURVEY §7: "the runner IS the TPU host process").

Engine location: a directory with an ``engine.json`` variant whose
``engineFactory`` names a ``module:callable`` (the sbt-built jar +
manifest of the reference collapses to an importable Python package).
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import sys
from typing import Any, Dict, Optional

from predictionio_tpu.workflow.create_workflow import WorkflowConfig


def _load_variant(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _workflow_config(args, variant: Dict[str, Any]) -> WorkflowConfig:
    factory = getattr(args, "engine_factory", None) or variant.get(
        "engineFactory", "")
    if not factory:
        raise ValueError(
            "no engine factory: set \"engineFactory\": \"module:callable\" "
            "in engine.json or pass --engine-factory")
    return WorkflowConfig(
        engine_id=getattr(args, "engine_id", None) or variant.get(
            "id", "default"),
        engine_version=getattr(args, "engine_version", None) or variant.get(
            "version", "default"),
        engine_variant=args.engine_variant,
        engine_factory=factory,
        batch=getattr(args, "batch", "") or "",
        skip_sanity_check=getattr(args, "skip_sanity_check", False),
        stop_after_read=getattr(args, "stop_after_read", False),
        stop_after_prepare=getattr(args, "stop_after_prepare", False),
    )


def cmd_build(args) -> int:
    """Sanity-check the engine dir: variant parses, factory imports, params
    typecheck (the sbt build + RegisterEngine analog, Console.scala:812-828)."""
    from predictionio_tpu.controller.evaluation import Evaluation
    from predictionio_tpu.workflow import core_workflow

    try:
        variant = _load_variant(args.engine_variant)
        config = _workflow_config(args, variant)
        factory = core_workflow.load_engine_factory(config.engine_factory)
        engine = factory()
        if isinstance(engine, Evaluation):
            engine = engine.engine
        engine.engine_params_from_variant(variant)
    except Exception as e:
        print(f"[ERROR] {e}", file=sys.stderr)
        return 1
    print("[INFO] Engine is ready for training.")
    return 0


def _apply_metrics_flag(args) -> None:
    """--metrics on|off -> the process-wide registry switch (None leaves
    the PIO_METRICS env default in place)."""
    flag = getattr(args, "metrics", None)
    if flag is not None:
        from predictionio_tpu.utils import metrics
        metrics.set_enabled(flag == "on")


def _apply_tracing_flags(args) -> None:
    """--tracing on|off + --trace-dir/$PIO_TRACE_DIR -> the tracing
    switch and the JSONL trace export (None leaves PIO_TRACING alone)."""
    from predictionio_tpu.utils import tracing

    flag = getattr(args, "tracing", None)
    if flag is not None:
        tracing.set_tracing_enabled(flag == "on")
    trace_dir = getattr(args, "trace_dir", None) \
        or os.environ.get("PIO_TRACE_DIR") or None
    if trace_dir:
        tracing.set_trace_dir(trace_dir)


def _apply_precision_flags(args) -> None:
    """--precision -> $PIO_ALS_PRECISION, --serve-precision ->
    $PIO_SERVE_PRECISION. The env vars are the single source of truth
    the per-call resolvers (ops/als.py, ops/serving.py) read, so the
    flags override engine.json params the same way the operator-set env
    would; None leaves any ambient env value in place."""
    precision = getattr(args, "precision", None)
    if precision:
        os.environ["PIO_ALS_PRECISION"] = precision
    serve_precision = getattr(args, "serve_precision", None)
    if serve_precision:
        os.environ["PIO_SERVE_PRECISION"] = serve_precision
    serve_kernel = getattr(args, "serve_kernel", None)
    if serve_kernel:
        os.environ["PIO_SERVE_KERNEL"] = serve_kernel
    # --batch-window -> $PIO_BATCH_WINDOW: the micro-batch dispatcher
    # resolves the budget at construction, same env-as-truth discipline
    batch_window = getattr(args, "batch_window", None)
    if batch_window is not None:
        if batch_window < 0:
            raise SystemExit("--batch-window must be >= 0")
        os.environ["PIO_BATCH_WINDOW"] = repr(float(batch_window))


def _apply_checkpoint_flags(args) -> None:
    """--checkpoint-every/-dir/-keep + --resume -> the PIO_CHECKPOINT_*
    env vars the per-call resolver (workflow/checkpoint.py) reads —
    the same env-as-truth discipline as the precision flags. When a
    checkpoint dir is active, SIGTERM/SIGINT become graceful
    preemption: finish the in-flight chunk, write a final checkpoint,
    exit 0."""
    every = getattr(args, "checkpoint_every", None)
    if every is not None and every < 1:
        raise SystemExit("--checkpoint-every must be >= 1")
    keep = getattr(args, "checkpoint_keep", None)
    if keep is not None and keep < 1:
        raise SystemExit("--checkpoint-keep must be >= 1")
    cdir = getattr(args, "checkpoint_dir", None)
    resume = bool(getattr(args, "resume", False))
    active_dir = (cdir or os.environ.get("PIO_CHECKPOINT_DIR", "")).strip()
    if (every is not None or resume) and not active_dir:
        raise SystemExit(
            "--checkpoint-every/--resume require --checkpoint-dir "
            "(or $PIO_CHECKPOINT_DIR)")
    # validation complete — only now touch the env: a refused
    # invocation must not leave half the knobs set behind it (in-
    # process callers would inherit a phantom $PIO_RESUME)
    if every is not None:
        os.environ["PIO_CHECKPOINT_EVERY"] = str(every)
    if cdir:
        os.environ["PIO_CHECKPOINT_DIR"] = cdir
    if keep is not None:
        os.environ["PIO_CHECKPOINT_KEEP"] = str(keep)
    if resume:
        os.environ["PIO_RESUME"] = "1"
    # graceful-drain handlers ONLY when a chunk cadence is actually
    # configured here (flag/env every, or --resume): a dir alone runs
    # the single-scan path with no boundary that would ever honor the
    # stop flag, and a swallowed SIGTERM that logs "will checkpoint"
    # while nothing will is worse than the default kill. (An engine
    # variant may still set ALSParams.checkpoint_every on its own —
    # checkpoints then land at every boundary and a hard kill stays
    # resumable; only the signal-drain nicety needs the CLI/env knob.)
    if active_dir and (
            every is not None or resume
            or os.environ.get("PIO_CHECKPOINT_EVERY", "").strip()):
        from predictionio_tpu.workflow import checkpoint

        checkpoint.clear_stop()
        checkpoint.install_signal_handlers()


def _train_progress_scope():
    """The `pio train` live meter: renders each chunk-boundary
    telemetry sample as a single ``\\r``-rewritten progress line on
    stderr. Active when stderr is a TTY, forced on/off with
    $PIO_TRAIN_PROGRESS; a plain nullcontext under
    PIO_TRAIN_TELEMETRY=0 (no samples would arrive anyway)."""
    import contextlib

    from predictionio_tpu.workflow import checkpoint, runlog

    forced = os.environ.get("PIO_TRAIN_PROGRESS", "").strip().lower()
    if forced in ("0", "false", "no", "off") \
            or not runlog.telemetry_enabled() \
            or not (forced in ("1", "true", "yes", "on")
                    or sys.stderr.isatty()):
        return contextlib.nullcontext()

    state = {"width": 0}

    def render(p):
        total = int(p.get("total") or 0)
        step = int(p.get("step") or 0)
        bar_w = 24
        fill = min(bar_w, int(bar_w * step / total)) if total else 0
        loss = p.get("loss")
        msg = (f"[{'#' * fill}{'-' * (bar_w - fill)}] "
               f"iter {step}/{total} "
               f"loss {'-' if loss is None else f'{loss:.6g}'} "
               f"({float(p.get('wallSeconds') or 0):.2f}s/chunk)")
        sys.stderr.write("\r" + msg.ljust(state["width"]))
        state["width"] = len(msg)
        if total and step >= total:
            sys.stderr.write("\n")
            state["width"] = 0
        sys.stderr.flush()

    return checkpoint.progress_scope(render)


def cmd_train(args) -> int:
    """Console train (Console.scala:834-842) -> create_workflow. A
    profile dir (--profile-dir / $PIO_PROFILE_DIR) captures a
    jax.profiler trace of the whole train pass, with JIT-compile
    count/time accounted in the metrics registry."""
    from predictionio_tpu.core.base import TrainingInterruption
    from predictionio_tpu.utils import metrics
    from predictionio_tpu.workflow.create_workflow import create_workflow

    from predictionio_tpu.utils.tracing import profile_trace, trace_scope

    _apply_tracing_flags(args)
    _apply_precision_flags(args)
    _apply_checkpoint_flags(args)
    try:
        # multi-host runtime (no-op on one host; parallel/distributed.py)
        from predictionio_tpu.parallel import distributed
        dist_cfg = distributed.DistributedConfig.from_args(args)
        if distributed.initialize(dist_cfg):
            print(f"[INFO] Joined distributed runtime: host "
                  f"{distributed.process_index()}/"
                  f"{distributed.process_count()}")
        variant = _load_variant(args.engine_variant)
        config = _workflow_config(args, variant)
        profile_dir = getattr(args, "profile_dir", None) \
            or os.environ.get("PIO_PROFILE_DIR") or None
        metrics.install_jit_compile_listener()
        # one trace root over the whole train pass: the DASE stage
        # spans (dase.read/prepare/train/eval) nest under it, and a
        # --trace-dir exports the tree next to the jax.profiler capture
        with profile_trace(profile_dir), \
                trace_scope("pio.train",
                            attributes={"variant": args.engine_variant},
                            slow_exempt=True), \
                _train_progress_scope():
            instance_id = create_workflow(config, variant=variant)
    except TrainingInterruption as e:
        print(f"[INFO] Training interrupted: {e}")
        return 0
    except Exception as e:
        print(f"[ERROR] Training failed: {e}", file=sys.stderr)
        return 1
    if instance_id is None:
        if not distributed.is_primary_host():
            print("[INFO] Secondary host: training complete; persistence "
                  "done by host 0.")
        else:
            print("[INFO] Training interrupted by a stop-after flag.")
        return 0
    print(f"[INFO] Training completed. Engine instance ID: {instance_id}")
    return 0


def _cmd_eval_grid(args) -> int:
    """``pio eval --grid grid.json``: the vmapped tuning lane. The grid
    file's ALSParams configs are validated LOUDLY (every unknown or
    non-sweepable field named, before any device work), the app's rate
    events are read once and leave-last-out split, and ONE device
    program trains every config against the shared bucketed tables —
    sized to the HBM budget, diverged configs masked out. Writes the
    leaderboard artifact (metric per config; winner pinned with its
    full EngineParams) to ``--grid-out``."""
    import numpy as np

    from predictionio_tpu.ops import als as _als
    from predictionio_tpu.ops import tuning as ops_tuning
    from predictionio_tpu.workflow import tuning as wf_tuning

    try:
        with open(args.grid, "r", encoding="utf-8") as f:
            spec = json.load(f)
    except (OSError, ValueError) as e:
        print(f"[ERROR] cannot read grid file {args.grid}: {e}",
              file=sys.stderr)
        return 1
    if not isinstance(spec, dict):
        print(f"[ERROR] {args.grid}: grid file must be a JSON object",
              file=sys.stderr)
        return 1
    unknown = sorted(set(spec) - {"base", "configs", "data"})
    if unknown:
        for key in unknown:
            print(f"[ERROR] {args.grid}: unknown section {key!r} "
                  "(expected: base, configs, data)", file=sys.stderr)
        return 1
    try:
        grid = ops_tuning.grid_from_spec(
            {k: spec[k] for k in ("base", "configs") if k in spec})
    except ops_tuning.GridConfigError as e:
        # the per-field loudness contract: one [ERROR] line per problem
        for line in str(e).splitlines():
            print(f"[ERROR] {args.grid}: {line.strip()}",
                  file=sys.stderr)
        return 1
    data_spec = spec.get("data") or {}
    app_name = data_spec.get("appName") or data_spec.get("app_name")
    if not app_name:
        print(f"[ERROR] {args.grid}: missing data.appName (the event "
              "app to tune against)", file=sys.stderr)
        return 1
    event_names = list(data_spec.get("eventNames", ["rate"]))

    from predictionio_tpu.data.store import PEventStore

    try:
        batch = PEventStore.find_columnar(
            app_name=app_name,
            channel_name=data_spec.get("channelName"),
            entity_type="user", event_names=event_names,
            target_entity_type="item", value_property="rating",
            default_value=1.0)
    except Exception as e:
        print(f"[ERROR] cannot read events for app {app_name!r}: {e}",
              file=sys.stderr)
        return 1
    if len(batch.entity_ids) == 0:
        print(f"[ERROR] app {app_name!r} has no "
              f"{'/'.join(event_names)} events to tune on",
              file=sys.stderr)
        return 1
    users, rows = np.unique(np.asarray(batch.entity_ids),
                            return_inverse=True)
    items, cols = np.unique(np.asarray(batch.target_ids),
                            return_inverse=True)
    vals = np.asarray(batch.values, dtype=np.float32)

    # leave-last-out holdout in stream order (the sliding-eval
    # protocol): each user's LAST interaction is the test target
    held: Dict[int, set] = {}
    train_mask = np.ones(len(rows), dtype=bool)
    order = np.argsort(rows, kind="stable")
    start = 0
    while start < len(order):
        end = start
        while end < len(order) and rows[order[end]] == rows[order[start]]:
            end += 1
        if end - start >= 2:
            last = order[end - 1]
            train_mask[last] = False
            held[int(rows[last])] = {int(cols[last])}
        start = end
    tr, tc, tv = rows[train_mask], cols[train_mask], vals[train_mask]
    if not len(tr):
        print(f"[ERROR] app {app_name!r}: no training interactions "
              "left after the leave-last-out split", file=sys.stderr)
        return 1

    user_side, item_side = _als.bucket_ratings_pair(
        tr, tc, tv, len(users), len(items))
    user_side, item_side = user_side.to_device(), item_side.to_device()

    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.templates.recommendation.engine import (
        DataSourceParams,
    )

    ep_base = EngineParams(
        data_source_params=("", DataSourceParams(
            app_name=str(app_name), event_names=tuple(event_names))))
    print(f"[INFO] grid eval: {grid.k} configs x "
          f"{int(grid.base.num_iterations)} iterations on "
          f"{len(tr)} train / {len(held)} held-out interactions "
          f"({len(users)} users, {len(items)} items)")
    from predictionio_tpu.data.storage.localfs import atomic_write_bytes

    out = args.grid_out

    def stream_partial(partial_board) -> None:
        # a killed sweep leaves the latest completed sub-batch's board
        # on disk — atomic, so readers never see a torn artifact
        atomic_write_bytes(
            out, json.dumps(partial_board, indent=2).encode("utf-8"))
        print(f"[INFO] partial leaderboard "
              f"({partial_board.get('batchesCompleted')}/"
              f"{len(partial_board.get('batches') or [])} "
              f"sub-batches) -> {out}")

    board = wf_tuning.run_grid(
        user_side, item_side, grid, train_rows=tr, train_cols=tc,
        held=held, topk=int(getattr(args, "topk", 10) or 10),
        engine_params_base=ep_base, on_partial=stream_partial)

    atomic_write_bytes(out, json.dumps(board, indent=2).encode("utf-8"))
    diverged = [r["config"] for r in board["rows"] if r["diverged"]]
    if diverged:
        print(f"[WARN] diverged configs masked out: {diverged}")
    w = board["winner"]
    if w is None:
        print("[ERROR] every config diverged — no winner",
              file=sys.stderr)
        return 1
    print(f"[INFO] winner: config {w['config']} {w['params']} "
          f"{board['metricName']}={w['metric']:.4f} "
          f"(ndcg@{board['k']}={w['ndcgAtK']:.4f}); leaderboard -> {out}")
    return 0


def cmd_eval(args) -> int:
    """Console eval (Console.scala:750-757): evaluation class + optional
    params-generator class -> run_evaluation. With ``--grid``, the
    vmapped multi-config tuning lane instead (:func:`_cmd_eval_grid`)."""
    if getattr(args, "grid", None):
        return _cmd_eval_grid(args)
    if not args.evaluation:
        print("[ERROR] eval needs an Evaluation class "
              "(module:callable) or --grid grid.json", file=sys.stderr)
        return 1
    from predictionio_tpu.controller.evaluation import (
        Evaluation, EngineParamsGenerator)
    from predictionio_tpu.data.storage.base import EvaluationInstance
    from predictionio_tpu.workflow import core_workflow, run_evaluation
    from predictionio_tpu.workflow.create_workflow import pio_env_vars

    try:
        evaluation = core_workflow.load_engine_factory(args.evaluation)()
        if not isinstance(evaluation, Evaluation):
            raise TypeError(f"{args.evaluation} is not an Evaluation")
        if args.engine_params_generator:
            generator = core_workflow.load_engine_factory(
                args.engine_params_generator)()
            if not isinstance(generator, EngineParamsGenerator):
                raise TypeError(f"{args.engine_params_generator} is not an "
                                "EngineParamsGenerator")
            params_list = generator.engine_params_list
        elif isinstance(evaluation, EngineParamsGenerator):
            params_list = evaluation.engine_params_list
        else:
            raise ValueError(
                "no engine params: pass an EngineParamsGenerator class or "
                "make the Evaluation also an EngineParamsGenerator")
    except Exception as e:
        print(f"[ERROR] {e}", file=sys.stderr)
        return 1

    now = _dt.datetime.now(tz=_dt.timezone.utc)
    instance = EvaluationInstance(
        id="", status="INIT", start_time=now, end_time=now,
        evaluation_class=args.evaluation,
        engine_params_generator_class=args.engine_params_generator or "",
        batch=getattr(args, "batch", "") or "",
        env=pio_env_vars(),
    )
    try:
        result = run_evaluation(
            evaluation.engine, params_list, instance, evaluation.evaluator,
            evaluation=evaluation)
    except Exception as e:
        print(f"[ERROR] Evaluation failed: {e}", file=sys.stderr)
        return 1
    print(f"[INFO] {result.to_one_liner()}")
    return 0


def cmd_deploy(args) -> int:
    """Console deploy (Console.scala:844-878): serve the given or latest
    COMPLETED engine instance until interrupted."""
    from predictionio_tpu.workflow import QueryServer, ServerConfig

    _apply_metrics_flag(args)
    _apply_tracing_flags(args)
    _apply_precision_flags(args)
    foldin = getattr(args, "foldin", "off") == "on"
    # no env write here: QueryServer.deploy() sets PIO_FOLDIN from
    # ServerConfig(foldin=True) before the model loads, and setting it
    # earlier would make deploy() capture "1" as the prior value —
    # defeating its own restore on stop()/failed deploy
    if args.feedback and not args.accesskey:
        # CreateServer.scala:452-455: feedback requires an access key
        print("[ERROR] Feedback loop cannot be enabled because accessKey "
              "is empty. Pass --accesskey.", file=sys.stderr)
        return 1
    variant_id, variant_version = "default", "default"
    if os.path.exists(args.engine_variant):
        variant = _load_variant(args.engine_variant)
        variant_id = variant.get("id", "default")
        variant_version = variant.get("version", "default")
    config = ServerConfig(
        engine_instance_id=args.engine_instance_id,
        engine_id=getattr(args, "engine_id", None) or variant_id,
        engine_version=(getattr(args, "engine_version", None)
                        or variant_version),
        engine_variant=args.engine_variant,
        ip=args.ip,
        port=args.port,
        feedback=args.feedback,
        event_server_ip=args.event_server_ip,
        event_server_port=args.event_server_port,
        access_key=args.accesskey,
        server_config_path=getattr(args, "server_config", None),
        foldin=foldin,
        slo_config=getattr(args, "slo_config", None),
    )
    fleet_n = int(getattr(args, "fleet", 1) or 1)
    try:
        if fleet_n > 1:
            from predictionio_tpu.fleet.balancer import QueryFleet

            server = QueryFleet(config, replicas=fleet_n).start()
        else:
            server = QueryServer(config).start()
    except Exception as e:
        print(f"[ERROR] Deploy failed: {e}", file=sys.stderr)
        return 1
    host, port = server.address
    if fleet_n > 1:
        print(f"[INFO] Engine is deployed on a {fleet_n}-replica fleet. "
              f"Engine API is live at {server.scheme}://{host}:{port}.")
    else:
        print(f"[INFO] Engine is deployed and running. Engine API is live "
              f"at {server.scheme}://{host}:{port}.")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_batchpredict(args) -> int:
    """Bulk offline scoring (the later releases' ``pio batchpredict``):
    queries from a JSONL file or synthesized from the event store, run
    through the full DASE serve path in restartable device-shaped
    chunks. See predictionio_tpu/batch/predict.py."""
    from predictionio_tpu.batch import (
        BatchPredictConfig,
        run_batch_predict,
        run_smoke,
    )

    _apply_metrics_flag(args)
    _apply_tracing_flags(args)
    _apply_precision_flags(args)
    if args.smoke:
        return run_smoke()
    if not args.output:
        print("[ERROR] --output is required (the shard/manifest "
              "directory).", file=sys.stderr)
        return 1
    try:
        base = json.loads(args.synthesize_base or "{}")
        if not isinstance(base, dict):
            raise ValueError("--synthesize-base must be a JSON object")
        variant_id, variant_version = "default", "default"
        if os.path.exists(args.engine_variant):
            variant = _load_variant(args.engine_variant)
            variant_id = variant.get("id", "default")
            variant_version = variant.get("version", "default")
        config = BatchPredictConfig(
            output_dir=args.output,
            engine_instance_id=args.engine_instance_id,
            engine_id=getattr(args, "engine_id", None) or variant_id,
            engine_version=(getattr(args, "engine_version", None)
                            or variant_version),
            engine_variant=args.engine_variant,
            input_path=args.input,
            synthesize_app=args.synthesize_app,
            synthesize_entity_type=args.synthesize_entity_type,
            synthesize_field=args.synthesize_field,
            synthesize_base=base,
            synthesize_channel=args.channel,
            chunk_size=args.chunk_size,
            query_partitions=args.query_partitions,
            format=args.format,
            batch=getattr(args, "batch", "") or "",
        )
        summary = run_batch_predict(config)
    except Exception as e:
        print(f"[ERROR] Batch predict failed: {e}", file=sys.stderr)
        return 1
    print(f"[INFO] Batch predict completed: {summary['queries']} queries "
          f"in {summary['chunks']} chunks "
          f"({summary['chunksScored']} scored, "
          f"{summary['chunksSkipped']} resumed) -> "
          f"{summary['outputDir']} "
          f"[{summary['queriesPerSec']} q/s scoring]")
    return 0


def cmd_undeploy(args) -> int:
    """Console undeploy (Console.scala:880-890): stop a running server.
    Probes HTTP first, then HTTPS, so it stops servers deployed with a
    TLS server.json without needing to know which scheme is live."""
    from predictionio_tpu.workflow import undeploy

    if undeploy(args.ip, args.port) \
            or undeploy(args.ip, args.port, scheme="https"):
        print("[INFO] Undeployed.")
        return 0
    print(f"[ERROR] Nothing at {args.ip}:{args.port} responded to /stop.",
          file=sys.stderr)
    return 1


def cmd_eventserver(args) -> int:
    """Console eventserver (Console.scala:741-745)."""
    import os

    from predictionio_tpu.data.api import EventServer, EventServerConfig

    _apply_metrics_flag(args)
    _apply_tracing_flags(args)  # $PIO_TRACE_DIR exports this side too
    service_key = getattr(args, "service_key", None) \
        or os.environ.get("PIO_EVENTSERVER_SERVICE_KEY") or None
    server = EventServer(EventServerConfig(
        ip=args.ip, port=args.port, stats=args.stats,
        service_key=service_key,
        server_config_path=getattr(args, "server_config", None))).start()
    host, port = server.address
    print(f"[INFO] Event Server is ready at {server.scheme}://{host}:{port}.")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_adminserver(args) -> int:
    """Console adminserver (Console.scala:747-751)."""
    from predictionio_tpu.tools.admin_server import AdminServer, AdminServerConfig

    server = AdminServer(AdminServerConfig(ip=args.ip, port=args.port))
    print(f"[INFO] Admin Server is ready at http://{args.ip}:{args.port}.")
    server.serve_forever()
    return 0


def cmd_dashboard(args) -> int:
    """Console dashboard (Console.scala:753-757)."""
    from predictionio_tpu.common import ServerConfig
    from predictionio_tpu.tools.dashboard import Dashboard, DashboardConfig

    server_config = ServerConfig.load(args.server_config) \
        if args.server_config else ServerConfig.load()
    server = Dashboard(DashboardConfig(ip=args.ip, port=args.port,
                                       server_config=server_config))
    scheme = "https" if server_config.ssl_certfile else "http"
    print(f"[INFO] Dashboard is ready at {scheme}://{args.ip}:{args.port}.")
    server.serve_forever()
    return 0
