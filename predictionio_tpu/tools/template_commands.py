"""``pio template`` subcommands: list/get.

Parity: ``tools/.../console/Template.scala:226-415`` — the reference
downloads engine templates from GitHub and personalizes the package name.
This environment has no egress, and templates here are importable packages
rather than sbt projects, so ``get`` scaffolds an engine directory wired
to a built-in template's factory instead of cloning.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict

BUILTIN_TEMPLATES: Dict[str, Dict] = {
    "recommendation": {
        "description": "Implicit-ALS top-N recommendation "
                       "(scala-parallel-recommendation parity)",
        "engineFactory":
            "predictionio_tpu.templates.recommendation:engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.recommendation:engine_factory",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "algorithms": [{
                "name": "als",
                "params": {"rank": 10, "numIterations": 10,
                           "lambda": 0.01, "seed": 3},
            }],
        },
    },
    "classification": {
        "description": "Naive Bayes classification from $set properties "
                       "(scala-parallel-classification parity)",
        "engineFactory":
            "predictionio_tpu.templates.classification:engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.classification:engine_factory",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "algorithms": [{"name": "naive", "params": {"lambda": 1.0}}],
        },
    },
    "similarproduct": {
        "description": "Item-to-item similarity on view events "
                       "(scala-parallel-similarproduct parity)",
        "engineFactory":
            "predictionio_tpu.templates.similarproduct:engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.similarproduct:engine_factory",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "algorithms": [{
                "name": "als",
                "params": {"rank": 10, "numIterations": 20, "seed": 3},
            }],
        },
    },
    "similarproduct-recommended-user": {
        "description": "Who-to-follow via ALS on follow events "
                       "(similarproduct recommended-user variant parity)",
        "engineFactory":
            "predictionio_tpu.templates.similarproduct"
            ":engine_factory_recommended_user",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.similarproduct"
                ":engine_factory_recommended_user",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "algorithms": [{
                "name": "als",
                "params": {"rank": 10, "numIterations": 20, "seed": 3},
            }],
        },
    },
    "helloworld": {
        "description": "L-flavor day->average-temperature engine "
                       "(experimental/scala-local-helloworld parity)",
        "engineFactory":
            "predictionio_tpu.templates.helloworld:engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.helloworld:engine_factory",
            "datasource": {"params": {"dataPath": "data.csv"}},
        },
    },
    "friendrecommendation": {
        "description": "Keyword-similarity friend/item acceptance on KDD "
                       "Cup 2012 data (experimental "
                       "scala-local-friend-recommendation parity)",
        "engineFactory":
            "predictionio_tpu.templates.friendrecommendation"
            ":engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.friendrecommendation"
                ":engine_factory",
            "datasource": {"params": {
                "itemFilePath": "data/item.txt",
                "userKeywordFilePath": "data/user_key_word.txt",
                "userActionFilePath": "data/user_action.txt"}},
        },
    },
    "similarproduct-dimsum": {
        "description": "Item-item cosine from the raw interaction matrix "
                       "(experimental similarproduct-dimsum parity)",
        "engineFactory":
            "predictionio_tpu.templates.similarproduct"
            ":engine_factory_dimsum",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.similarproduct"
                ":engine_factory_dimsum",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "algorithms": [{"name": "dimsum",
                            "params": {"threshold": 0.1}}],
        },
    },
    "regression": {
        "description": "L-flavor OLS linear regression from a data file "
                       "(experimental/scala-local-regression parity)",
        "engineFactory":
            "predictionio_tpu.templates.regression:engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.regression:engine_factory",
            "datasource": {"params": {"filepath": "lr_data.txt"}},
            "preparator": {"params": {"n": 0, "k": 0}},
        },
    },
    "ecommercerecommendation": {
        "description": "ALS + business-rule filters at predict time "
                       "(scala-parallel-ecommercerecommendation parity)",
        "engineFactory":
            "predictionio_tpu.templates.ecommercerecommendation"
            ":engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.ecommercerecommendation"
                ":engine_factory",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "algorithms": [{
                "name": "als",
                "params": {"rank": 10, "numIterations": 20, "seed": 3},
            }],
        },
    },
    "sequentialrec": {
        "description": "SASRec-style next-item prediction over "
                       "per-user event sequences (net-new; causal "
                       "transformer on the ring/Ulysses attention "
                       "kernels, served via the device top-k store)",
        "engineFactory":
            "predictionio_tpu.templates.sequentialrec:engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.sequentialrec"
                ":engine_factory",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "preparator": {"params": {"maxSeqLen": 32}},
            "algorithms": [{
                "name": "seqrec",
                "params": {"rank": 32, "nLayers": 2, "nHeads": 2,
                           "numSteps": 300, "seed": 7},
            }],
        },
    },
    "twostage": {
        "description": "Two-stage serving: ALS retrieves N candidates, "
                       "the seqrec encoder re-ranks them — fused into "
                       "ONE device program per query batch (net-new; "
                       "ROADMAP item 5)",
        "engineFactory":
            "predictionio_tpu.templates.twostage:engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.twostage:engine_factory",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "preparator": {"params": {"maxSeqLen": 32}},
            "algorithms": [{
                "name": "als",
                "params": {"rank": 32, "numIterations": 10, "seed": 3},
            }, {
                "name": "seqrec",
                "params": {"rank": 32, "nLayers": 2, "nHeads": 2,
                           "numSteps": 300, "seed": 7},
            }],
        },
    },
    "textclassification": {
        "description": "Text -> label: hashed embedding table + LR "
                       "trained on device, NB over token counts "
                       "(net-new; BASELINE.json configs[4])",
        "engineFactory":
            "predictionio_tpu.templates.textclassification"
            ":engine_factory",
        "variant": {
            "id": "default",
            "version": "default",
            "engineFactory":
                "predictionio_tpu.templates.textclassification"
                ":engine_factory",
            "datasource": {"params": {"appName": "INVALID_APP_NAME"}},
            "preparator": {"params": {"vocabSize": 4096,
                                      "maxTokens": 64}},
            "algorithms": [{
                "name": "lr",
                "params": {"embeddingDim": 64, "epochs": 30, "seed": 0},
            }],
        },
    },
}


def dispatch(args) -> int:
    cmd = getattr(args, "template_command", None)
    if cmd == "list":
        return template_list()
    if cmd == "get":
        return template_get(args.name, args.directory)
    print("usage: pio template {list,get} ...", file=sys.stderr)
    return 2


def template_list() -> int:
    print(f"[INFO] {'Template':<26} | Description")
    for name, t in BUILTIN_TEMPLATES.items():
        print(f"[INFO] {name:<26} | {t['description']}")
    return 0


def template_get(name: str, directory: str) -> int:
    t = BUILTIN_TEMPLATES.get(name)
    if t is None:
        print(f"[ERROR] Template {name} not found. Try 'pio template list'.",
              file=sys.stderr)
        return 1
    os.makedirs(directory, exist_ok=True)
    variant_path = os.path.join(directory, "engine.json")
    if os.path.exists(variant_path):
        print(f"[ERROR] {variant_path} already exists. Aborting.",
              file=sys.stderr)
        return 1
    with open(variant_path, "w", encoding="utf-8") as f:
        json.dump(t["variant"], f, indent=2)
        f.write("\n")
    with open(os.path.join(directory, "template.json"), "w",
              encoding="utf-8") as f:
        json.dump({"pio": {"version": {"min": "0.2.0"}}}, f)
        f.write("\n")
    print(f"[INFO] Engine template {name} is now ready at {directory}.")
    print("[INFO] Edit engine.json (set appName), then: "
          "pio build && pio train && pio deploy")
    return 0
