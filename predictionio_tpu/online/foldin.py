"""The fold-in consumer: tail the event stream, solve, patch the store.

Lifecycle (one daemon thread per deployed engine with ``--foldin on``):

1. **Tail** — poll ``LEvents.find_since`` from the cursor minted at
   start (``tail_cursor``: only events AFTER deployment fold — history
   is already in the trained factors). The cursor shape is the
   backend's own (memory sequence / sqlite rowid / jsonlfs byte
   watermark / opaque over the resthttp wire).
2. **Accumulate** — rating events (the datasource's event names,
   user->item with a numeric value property) mark their user touched;
   everything else is ignored.
3. **Fold** — when touched users are pending and either the cadence
   (``PIO_FOLDIN_INTERVAL``) elapsed or the pending-event count crossed
   ``PIO_FOLDIN_COUNT``: gather each touched user's FULL rating set
   from the store (indexed per-entity read), solve all of them in one
   jitted batch-k dispatch (:func:`~predictionio_tpu.ops.als.
   fold_in_users` — the ALX normal-equations half-step against the
   fixed item factors, same fp32/bf16 precision policy as training),
   and patch the live ``DeviceTopK`` store
   (:meth:`~predictionio_tpu.ops.serving.DeviceTopK.patch_users`:
   donation-style scatter, lock-coordinated with the micro-batchers so
   in-flight queries never see a torn store). Unknown users grow the
   store via the power-of-two bucket ladder and land in the model's
   ``user_map`` only AFTER the store holds their row.

   Precision interplay: the solve always runs the TRAINING lane
   (fp32/bf16 per ``ALSParams.precision``) whatever the serving store
   holds — an int8 store (``PIO_SERVE_PRECISION=int8``) hands the
   solve a dequantized fp32 item view (``DeviceTopK.item_factors``)
   and ``patch_users`` re-quantizes the fresh rows with RECOMPUTED
   per-row absmax scales under the same ``_store_lock`` swap, so a
   folded row is bit-identical to what quantize-at-load would have
   produced for the same factors.

Degradation (PR-7 semantics): a failing tail read flips ``stale`` —
serving continues from the last-good factors and the query server
stamps responses ``degradedReasons: ["foldin_stale"]``; the next
successful read clears it. Every fold is a ``pio.foldin`` trace root
with gather/solve/patch child spans, and the ``pio_foldin_*`` metric
family (folds, users patched, event->servable freshness histogram)
feeds ``/metrics`` and ``/stats.json``.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.ops.als import ALSParams, fold_in_users
from predictionio_tpu.utils import device_telemetry, metrics
from predictionio_tpu.utils.resilience import _env_float
from predictionio_tpu.utils.tracing import span, trace_scope

logger = logging.getLogger("pio.foldin")

UTC = _dt.timezone.utc

# creation timestamps kept for the freshness histogram are capped: a
# catch-up burst must not hold one float per backlog event
_FRESHNESS_SAMPLE_CAP = 4096

# consecutive fold failures before the re-merged batch is dropped
# (dropped users re-enter on their next event)
_MAX_FAILED_ROUNDS = 3


@dataclasses.dataclass(frozen=True)
class FoldInConfig:
    """What to tail and when to fold.

    ``interval`` (seconds, ``PIO_FOLDIN_INTERVAL``, default 2.0) is the
    fold cadence: pending deltas are solved at most this often — unless
    ``count_threshold`` (``PIO_FOLDIN_COUNT``, default 64) pending
    events accumulate first, which folds immediately (a hot stream must
    not wait out the clock). The tail itself is polled a few times per
    interval so a fold fires close to the cadence boundary, not one
    poll late."""

    app_name: str
    channel_name: Optional[str] = None
    event_names: Tuple[str, ...] = ("rate",)
    entity_type: str = "user"
    target_entity_type: str = "item"
    value_property: Optional[str] = "rating"
    default_value: float = 1.0
    interval: float = 2.0
    count_threshold: int = 64
    tail_batch: int = 10_000
    # the preparator's per-row truncation, mirrored at fold time: an
    # engine trained with max_len must fold truncated or long-history
    # users solve a different objective than their trained rows
    max_len: Optional[int] = None

    @classmethod
    def from_env(cls, **kwargs) -> "FoldInConfig":
        kwargs.setdefault("interval",
                          _env_float("PIO_FOLDIN_INTERVAL", 2.0))
        kwargs.setdefault("count_threshold",
                          int(_env_float("PIO_FOLDIN_COUNT", 64)))
        return cls(**kwargs)


class FoldInConsumer:
    """Background fold-in for ONE deployed model (see module docstring).

    ``model`` must expose the ALS-template model surface: ``user_map`` /
    ``item_map`` (StringIndexBiMap), ``seen`` (user idx -> item idx
    array) and ``device_server()`` returning a store with
    ``patch_users`` (DeviceTopK). ``als_params`` carries the SAME
    hyperparameters the model trained with — the fold-in solve is the
    training half-step, and a different lambda/alpha would silently
    solve a different objective.
    """

    def __init__(self, model: Any, config: FoldInConfig,
                 als_params: Optional[ALSParams] = None,
                 patch_lock: Optional[threading.Lock] = None):
        self._model = model
        self._cfg = config
        self._params = als_params
        # serializes _patch's read-assign-append on user_map; a
        # composite whose targets SHARE one vocabulary (the two-stage
        # deployment) passes the same lock to every sharing consumer,
        # else two tails folding the same new user race the append
        self._patch_lock = patch_lock or threading.Lock()
        # model-provided solve hook (e.g. the sequentialrec template's
        # re-encode): when present it replaces the ALS half-step, and
        # ``foldin_time_ordered`` asks the gather to hand histories in
        # EVENT-TIME order (sequence encoders are order-sensitive; the
        # ALS normal equations are not)
        self._fold_hook = getattr(model, "fold_in_rows", None)
        self._ordered = bool(getattr(model, "foldin_time_ordered",
                                     False))
        if self._fold_hook is None and als_params is None:
            raise ValueError(
                "FoldInConsumer needs either ALSParams (the training "
                "half-step lane) or a model with fold_in_rows (the "
                "model-encoder lane)")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cursor: Optional[Dict] = None
        self._scope: Optional[Tuple[int, Optional[int]]] = None
        # pending user id -> delta event count since the last fold
        self._pending: Dict[str, int] = {}
        self._pending_events = 0
        self._fresh_ts: List[float] = []
        self._last_fold = time.monotonic()
        # consecutive failed folds: re-merged batches retry a bounded
        # number of times, then drop (a poison batch must not kill
        # fold-in for every OTHER user forever)
        self._failed_rounds = 0
        self._stats_lock = threading.Lock()
        self.stale = False
        self.folds = 0
        self.fold_errors = 0
        self.tail_errors = 0
        self.users_patched = 0
        self.new_users = 0
        self.events_folded = 0
        self.last_fold_at: Optional[_dt.datetime] = None
        # device µs of the most recent fold solve (flight recorder)
        self.last_solve_device_us: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FoldInConsumer":
        """Resolve the scope, pin the cursor at the CURRENT stream end
        (history up to the training read is inside the trained factors)
        and start the tail thread. Raises early — at deploy, not first
        fold — when the serving backend cannot be patched or the tail
        is unsupported.

        Known window: events that arrive between the training read and
        this deploy are behind the cursor AND absent from the trained
        factors. A user touched by any post-deploy event is re-solved
        from their FULL history (the gather reads the store, not the
        tail), so one later event heals the gap for that user; only a
        user whose entire activity falls inside the window stays
        unservable until the next train or their next event."""
        from predictionio_tpu.data.store import app_name_to_id

        server = self._model.device_server()
        if not hasattr(server, "patch_users"):
            raise ValueError(
                "online fold-in requires an updatable device factor "
                f"store; {type(server).__name__} has no patch_users — "
                "deploy with --foldin on (forces DeviceTopK) and drop "
                "PIO_SERVING_BACKEND=host")
        if not getattr(server, "growable", True):
            # refuse at deploy, not first unknown user: a non-growable
            # store's refusal inside a fold would poison every batch
            # that contains a new user. (Mesh-sharded DeviceTopK stores
            # grow by RESHARDING since ISSUE 15, so sharded deploys
            # fold in like single-chip ones.)
            raise ValueError(
                "online fold-in requires a growable user factor store; "
                f"{type(server).__name__} cannot grow its user rows")
        self._scope = app_name_to_id(self._cfg.app_name,
                                     self._cfg.channel_name)
        self._cursor = self._levents().tail_cursor(*self._scope)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="pio-foldin")
        self._thread.start()
        logger.info(
            "fold-in consumer started: app=%s channel=%s interval=%.2fs "
            "count=%d", self._cfg.app_name, self._cfg.channel_name,
            self._cfg.interval, self._cfg.count_threshold)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            return {
                "folds": self.folds,
                "foldErrors": self.fold_errors,
                "tailErrors": self.tail_errors,
                "usersPatched": self.users_patched,
                "newUsers": self.new_users,
                "eventsFolded": self.events_folded,
                "pendingEvents": self._pending_events,
                "stale": self.stale,
                "lastFoldAt": None if self.last_fold_at is None
                else self.last_fold_at.isoformat(),
                "lastSolveDeviceUs": self.last_solve_device_us,
                "intervalSec": self._cfg.interval,
                "countThreshold": self._cfg.count_threshold,
                "cursor": self._cursor,
            }

    # -- the tail loop -----------------------------------------------------

    @staticmethod
    def _levents():
        from predictionio_tpu.data import storage

        return storage.get_levents()

    def _run(self) -> None:
        poll = min(self._cfg.interval, 0.25) if self._cfg.interval > 0 \
            else 0.25
        while not self._stop.wait(poll):
            try:
                self._cycle()
            except Exception:
                # belt-and-braces: the loop must survive anything
                logger.exception("fold-in cycle failed")

    def _cycle(self) -> None:
        try:
            while not self._stop.is_set():
                events, self._cursor = self._levents().find_since(
                    *self._scope, cursor=self._cursor,
                    limit=self._cfg.tail_batch)
                if self.stale:
                    with self._stats_lock:
                        self.stale = False
                    metrics.FOLDIN_STALE.set(0)
                    logger.info("fold-in tail recovered")
                self._ingest(events)
                if len(events) < self._cfg.tail_batch or \
                        self._pending_events >= self._cfg.count_threshold:
                    break
        except Exception as e:
            # a failing tail must NOT take serving down: flag stale
            # (responses go out degraded from the last-good factors)
            # and try again next poll — the DAO layer's retries and
            # breaker already absorbed what was absorbable
            first = not self.stale
            with self._stats_lock:
                self.stale = True
                self.tail_errors += 1
            metrics.FOLDIN_STALE.set(1)
            metrics.FOLDIN_TAIL_ERRORS.inc()
            if first:
                logger.warning("fold-in tail read failed (serving "
                               "continues degraded): %s", e)
            return
        now = time.monotonic()
        if self._pending and (
                self._pending_events >= self._cfg.count_threshold
                or now - self._last_fold >= self._cfg.interval):
            self._fold()

    def _ingest(self, events) -> None:
        cfg = self._cfg
        names = set(cfg.event_names)
        now = time.time()
        for e in events:
            if e.event not in names or e.entity_type != cfg.entity_type \
                    or e.target_entity_type != cfg.target_entity_type \
                    or not e.target_entity_id:
                continue
            self._pending[e.entity_id] = \
                self._pending.get(e.entity_id, 0) + 1
            self._pending_events += 1
            if len(self._fresh_ts) < _FRESHNESS_SAMPLE_CAP:
                t = e.creation_time or e.event_time
                self._fresh_ts.append(min(t.timestamp(), now))

    # -- the fold ----------------------------------------------------------

    def _gather(self, user_ids: List[str]):
        """Each touched user's FULL rating set from the store, mapped
        onto item indices. Items the model has never seen carry no
        factors and are skipped; a user left with zero known items is
        dropped from this fold (their next event against a known item
        re-touches them).

        Read shape: backends that declare ``indexed_entity_reads``
        (sqlite) answer an ``entity_id``-filtered find from an index,
        so per-user reads are cheap. Scan-based backends (memory /
        jsonlfs / resthttp) pay a FULL-store pass per find — there a
        catch-up fold of k users must not cost k whole-store scans
        inside the live query server, so beyond a handful of users one
        shared scan is bucketed client-side instead."""
        cfg = self._cfg
        item_map = self._model.item_map
        le = self._levents()
        per_user: Dict[str, Tuple[List[int], List[float], List[float]]] \
            = {uid: ([], [], []) for uid in user_ids}
        ordered = self._ordered

        def take(bucket, e) -> None:
            idx = item_map.get(e.target_entity_id)
            if idx is None:
                return
            raw = e.properties.fields.get(cfg.value_property) \
                if cfg.value_property else None
            try:
                val = float(raw) if raw is not None \
                    else cfg.default_value
            except (TypeError, ValueError):
                val = cfg.default_value
            bucket[0].append(int(idx))
            bucket[1].append(val)
            if ordered:
                bucket[2].append(e.event_time.timestamp())

        find_kwargs = dict(
            channel_id=self._scope[1], entity_type=cfg.entity_type,
            event_names=list(cfg.event_names),
            target_entity_type=cfg.target_entity_type)
        if getattr(le, "indexed_entity_reads", False) \
                or len(user_ids) <= 4:
            for uid in user_ids:
                for e in le.find(self._scope[0], entity_id=uid,
                                 **find_kwargs):
                    take(per_user[uid], e)
        else:
            for e in le.find(self._scope[0], **find_kwargs):
                bucket = per_user.get(e.entity_id)
                if bucket is not None:
                    take(bucket, e)
        kept_ids: List[str] = []
        cols_list: List[np.ndarray] = []
        vals_list: List[np.ndarray] = []
        for uid in user_ids:
            cols, vals, times = per_user[uid]
            if not cols:
                continue
            kept_ids.append(uid)
            c = np.asarray(cols, dtype=np.int64)
            v = np.asarray(vals, dtype=np.float32)
            if ordered:
                # stable: equal timestamps keep the scan's arrival order
                o = np.argsort(np.asarray(times, dtype=np.float64),
                               kind="stable")
                c, v = c[o], v[o]
            cols_list.append(c)
            vals_list.append(v)
        return kept_ids, cols_list, vals_list

    def _fold(self) -> None:
        pending, self._pending = self._pending, {}
        n_events, self._pending_events = self._pending_events, 0
        fresh_ts, self._fresh_ts = self._fresh_ts, []
        self._last_fold = time.monotonic()
        model = self._model
        try:
            with trace_scope("pio.foldin",
                             attributes={"users": len(pending),
                                         "events": n_events},
                             slow_exempt=True):
                with span("foldin.gather",
                          attributes={"users": len(pending)}):
                    kept_ids, cols_list, vals_list = self._gather(
                        list(pending))
                if not kept_ids:
                    return
                server = model.device_server()
                with span("foldin.solve",
                          attributes={"users": len(kept_ids)}) as ssp:
                    if self._fold_hook is not None:
                        # model-encoder lane: re-encode the touched
                        # users' (time-ordered) sequences on device.
                        # The hook records no flight record of its own,
                        # so do NOT consult last_record() here — under
                        # live traffic it would hand back a concurrent
                        # QUERY dispatch's record and publish a wrong
                        # lane/deviceUs as the fold solve's
                        rows = self._fold_hook(cols_list, vals_list)
                        rec = None
                    else:
                        rows = fold_in_users(server.item_factors,
                                             cols_list, vals_list,
                                             self._params,
                                             max_len=self._cfg.max_len)
                        # the solve's flight record (device-telemetry
                        # PR 12): fold_in_users just recorded the
                        # "foldin"-lane dispatch; pin it to the span so
                        # a slow fold's trace names its bucket shape +
                        # device time, and keep the µs for stats()
                        rec = device_telemetry.last_record() \
                            if device_telemetry.enabled() else None
                    if rec is not None:
                        if ssp is not None:
                            ssp.attributes["dispatch"] = rec
                        with self._stats_lock:
                            self.last_solve_device_us = rec["deviceUs"]
                with span("foldin.patch",
                          attributes={"users": len(kept_ids)}):
                    known, new = self._patch(server, kept_ids, cols_list,
                                             rows)
            now = time.time()
            self._failed_rounds = 0
            with self._stats_lock:
                self.folds += 1
                self.users_patched += known + new
                self.new_users += new
                self.events_folded += n_events
                self.last_fold_at = _dt.datetime.now(tz=UTC)
            metrics.FOLDIN_FOLDS.inc(status="ok")
            if known:
                metrics.FOLDIN_USERS.inc(amount=known, kind="known")
            if new:
                metrics.FOLDIN_USERS.inc(amount=new, kind="new")
            metrics.FOLDIN_EVENTS.inc(amount=n_events)
            for t in fresh_ts:
                metrics.FOLDIN_FRESHNESS.observe(max(0.0, now - t))
        except Exception:
            # put the batch back: the cursor already advanced past these
            # events, so dropping the touched-user set here would leave
            # them unfolded until their NEXT event. Re-merging retries
            # the whole batch at the next cadence instead (gather reads
            # full histories, so a re-fold is exact, not additive) —
            # BOUNDED: a batch that fails _MAX_FAILED_ROUNDS times in a
            # row is dropped, or one poison user would stop every other
            # user's folds forever (dropped users heal on their next
            # event, which re-touches them).
            self._failed_rounds += 1
            with self._stats_lock:
                self.fold_errors += 1
            if self._failed_rounds >= _MAX_FAILED_ROUNDS:
                self._failed_rounds = 0
                metrics.FOLDIN_FOLDS.inc(status="dropped")
                logger.exception(
                    "fold-in batch failed %d consecutive times; "
                    "DROPPING %d touched users (they re-enter on their "
                    "next event)", _MAX_FAILED_ROUNDS, len(pending))
            else:
                for uid, c in pending.items():
                    self._pending[uid] = self._pending.get(uid, 0) + c
                self._pending_events += n_events
                self._fresh_ts = (fresh_ts
                                  + self._fresh_ts)[:_FRESHNESS_SAMPLE_CAP]
                metrics.FOLDIN_FOLDS.inc(status="error")
                logger.exception(
                    "fold-in batch failed (serving continues from the "
                    "previous factors; batch retries next cadence)")

    def _patch(self, server, kept_ids: List[str],
               cols_list: List[np.ndarray],
               rows: np.ndarray) -> Tuple[int, int]:
        """Write the solved rows into the live store and publish the new
        users. Order is load-bearing: the store is patched (and grown)
        BEFORE new labels land in ``user_map``, so a racing predict
        never resolves an index the store does not hold. The whole
        read-assign-append runs under ``patch_lock`` so two consumers
        sharing one vocabulary assign each new user exactly one row."""
        model = self._model
        user_map = model.user_map
        with self._patch_lock:
            uidxs: List[int] = []
            new_labels: List[str] = []
            next_idx = len(user_map)
            for uid in kept_ids:
                idx = user_map.get(uid)
                if idx is None:
                    idx = next_idx
                    next_idx += 1
                    new_labels.append(uid)
                uidxs.append(int(idx))
            seen_updates = {
                uidx: np.unique(cols).astype(np.int64)
                for uidx, cols in zip(uidxs, cols_list)}
            server.patch_users(np.asarray(uidxs, dtype=np.int64), rows,
                               seen_items=seen_updates)
            seen = getattr(model, "seen", None)
            if isinstance(seen, dict):
                seen.update(seen_updates)
            if new_labels:
                user_map.append(new_labels)
        return len(kept_ids) - len(new_labels), len(new_labels)


class CompositeFoldInConsumer:
    """Fold-in for EVERY qualifying model of a multi-algorithm
    deployment (ISSUE 20): each target keeps its own
    :class:`FoldInConsumer` — its own cursor, its own solve lane, so
    the ALS half-step and a seqrec re-encode coexist, each patching its
    own (facet of the) device store — while this wrapper presents the
    QueryServer's one-consumer surface (start/stop/stats/stale)."""

    def __init__(self, consumers: List[FoldInConsumer]):
        if not consumers:
            raise ValueError(
                "CompositeFoldInConsumer needs at least one consumer")
        self._consumers = list(consumers)

    @property
    def consumers(self) -> List[FoldInConsumer]:
        return list(self._consumers)

    def start(self) -> "CompositeFoldInConsumer":
        started: List[FoldInConsumer] = []
        try:
            for c in self._consumers:
                c.start()
                started.append(c)
        except Exception:
            # start() raises at deploy (not first fold) — a half-
            # started composite must not leak tail threads
            for c in started:
                c.stop()
            raise
        return self

    def stop(self, timeout: float = 5.0) -> None:
        for c in self._consumers:
            c.stop(timeout=timeout)

    @property
    def stale(self) -> bool:
        return any(c.stale for c in self._consumers)

    def stats(self) -> Dict[str, Any]:
        per = [c.stats() for c in self._consumers]
        out = dict(per[0])
        for other in per[1:]:
            for key in ("folds", "foldErrors", "tailErrors",
                        "usersPatched", "newUsers", "eventsFolded",
                        "pendingEvents"):
                out[key] += other[key]
            out["stale"] = bool(out["stale"] or other["stale"])
            stamps = [t for t in (out["lastFoldAt"],
                                  other["lastFoldAt"]) if t]
            out["lastFoldAt"] = max(stamps) if stamps else None
        out["targets"] = per
        return out


def attach_foldin(deployment: Any,
                  interval: Optional[float] = None,
                  count_threshold: Optional[int] = None) -> Any:
    """Build the fold-in consumer(s) for a loaded deployment
    (``workflow.create_server.Deployment``): EVERY algorithm whose
    model exposes the ALS device-serving surface is a fold-in target
    (one algorithm on classic deployments; BOTH stages of a two-stage
    deployment, whose facets route the writes to their half of the
    fused store), its ``ALSParams`` or model-side ``fold_in_rows``
    hook is the solve, and the datasource params name the (app,
    channel, event names) to tail. Returns one
    :class:`FoldInConsumer`, or a :class:`CompositeFoldInConsumer`
    over several. Raises when no deployed algorithm qualifies, or when
    a qualifying one has no usable solve — ``--foldin on`` on an
    incompatible engine must fail at deploy, not silently no-op."""
    targets = [(i, model) for i, model in enumerate(deployment.models)
               if all(hasattr(model, a) for a in
                      ("user_map", "item_map", "device_server"))]
    if not targets:
        raise ValueError(
            "--foldin on: no deployed algorithm serves an ALS-style "
            "device model (user_map/item_map/device_server); online "
            "fold-in has nothing to patch")
    dsp = deployment.engine_params.data_source_params[1]
    app_name = getattr(dsp, "app_name", None)
    if not app_name:
        raise ValueError(
            "--foldin on: the datasource params carry no app_name; the "
            "fold-in consumer cannot resolve which event stream to tail")
    prep = deployment.engine_params.preparator_params[1]
    raw_max_len = getattr(prep, "max_len", None)
    kwargs: Dict[str, Any] = dict(
        app_name=app_name,
        channel_name=getattr(dsp, "channel_name", None),
        event_names=tuple(getattr(dsp, "event_names", ("rate",))),
        max_len=None if raw_max_len is None else int(raw_max_len))
    if interval is not None:
        kwargs["interval"] = float(interval)
    if count_threshold is not None:
        kwargs["count_threshold"] = int(count_threshold)
    config = FoldInConfig.from_env(**kwargs)
    consumers: List[FoldInConsumer] = []
    # one patch lock per DISTINCT user_map object: two-stage targets
    # share their vocabulary, and concurrent tails must not both
    # append the same new user to it
    locks: List[Tuple[Any, threading.Lock]] = []

    def _lock_for(user_map: Any) -> threading.Lock:
        for owner, lock in locks:
            if owner is user_map:
                return lock
        lock = threading.Lock()
        locks.append((user_map, lock))
        return lock

    for i, model in targets:
        _, aparams = deployment.engine_params.algorithm_params_list[i]
        has_hook = callable(getattr(model, "fold_in_rows", None))
        if not has_hook and not isinstance(aparams, ALSParams):
            # refuse rather than guess: the fold-in solve is the
            # training half-step, and hyperparameters inferred by
            # getattr-with-defaults could silently solve a DIFFERENT
            # objective than the one the deployed factors were trained
            # under. A model that carries its OWN solve (fold_in_rows
            # — e.g. the sequentialrec re-encode, whose
            # hyperparameters travel inside the model) needs no
            # ALSParams.
            raise ValueError(
                "--foldin on: the deployed algorithm's params "
                f"({type(aparams).__name__}) are not ALSParams and the "
                "model has no fold_in_rows hook, so the fold-in solve "
                "cannot take its hyperparameters from training; give "
                "the algorithm ALSParams (or a subclass), or a "
                "model-side fold_in_rows encoder, to enable online "
                "fold-in")
        consumers.append(FoldInConsumer(
            model, config,
            aparams if isinstance(aparams, ALSParams) else None,
            patch_lock=_lock_for(model.user_map)))
    if len(consumers) == 1:
        return consumers[0]
    return CompositeFoldInConsumer(consumers)


__all__ = ["CompositeFoldInConsumer", "FoldInConfig", "FoldInConsumer",
           "attach_foldin"]
