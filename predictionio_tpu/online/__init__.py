"""Online fold-in: fresh user factors inside the deployed server.

ROADMAP item 3 — everything upstream of this package is batch: a new
user or a just-ingested event is invisible to serving until the next
full ``pio train`` + redeploy. This package closes that gap: a
background consumer tails the event stream per (app, channel) through
the storage layer's cursor reads (``LEvents.find_since``, all four
event backends), accumulates per-user rating deltas, and on a
configurable cadence solves the affected user rows against the FIXED
item factors with the jitted batch-k fold-in kernel
(:func:`predictionio_tpu.ops.als.fold_in_users`) — then patches the
live :class:`~predictionio_tpu.ops.serving.DeviceTopK` store in place.
New users are servable within seconds of their first events, with no
``/reload`` and no retrain.
"""

from predictionio_tpu.online.foldin import (  # noqa: F401
    FoldInConfig,
    FoldInConsumer,
    attach_foldin,
)
