"""predictionio_tpu — a TPU-native ML serving & lifecycle framework.

A ground-up re-design of the capability surface of PredictionIO
(reference: Scala/Spark, ``/root/reference``) for TPU hardware:

- DASE pipeline (DataSource -> Preparator -> Algorithm(s) -> Serving)
  with typed params and a train/eval/deploy lifecycle
  (cf. reference ``core/src/main/scala/io/prediction/controller/Engine.scala:80-86``).
- Append-only event store with ``$set/$unset/$delete`` entity-property
  aggregation (cf. ``data/.../storage/Event.scala``, ``LEventAggregator.scala``).
- TPU compute path: JAX/XLA/Pallas kernels sharded over a
  ``jax.sharding.Mesh`` replace Spark/MLlib jobs; XLA collectives over
  ICI replace Spark shuffles.
- Host-side data plane, REST servers (events/queries), CLI, evaluation
  and hyperparameter tuning.

Nothing here is a port: the architecture is JAX-first (functional
transforms, SPMD over meshes, static shapes), the runtime is Python +
C++ (ctypes) instead of JVM/akka, and persistence uses numpy/orbax
instead of Kryo.
"""

__version__ = "0.2.0"

from predictionio_tpu.data.event import Event, EventValidationError, validate_event
from predictionio_tpu.data.datamap import DataMap, PropertyMap, EntityMap
from predictionio_tpu.data.bimap import BiMap
