"""Multi-host runtime: the cluster-launch plane of the framework.

Reference analog: the `pio` CLI assembles a ``spark-submit`` command that
carries the whole cluster topology (``tools/src/main/scala/io/prediction/
tools/Runner.scala:92-210``); Spark's driver/executor processes then form
the cluster. Here the runner *is* the host process: every host runs the
same ``pio train ... --num-hosts K --coordinator HOST:PORT --process-id i``
command, :func:`initialize` connects them over DCN via
``jax.distributed.initialize``, and from then on ``jax.devices()`` is the
GLOBAL device set, meshes span all hosts, and XLA routes collectives over
ICI within a host/slice and DCN across hosts (SURVEY §2.6 comm row).

Single-process is the degenerate case: :func:`initialize` is a no-op when
``num_hosts <= 1`` and no coordinator is given, so the same engine code
runs unchanged on one host (the path every test and the driver's
``dryrun_multichip`` exercise).

Launch recipe (K hosts, same code on each)::

    # host 0 (also the coordinator)
    pio train ... --num-hosts K --coordinator host0:8476 --process-id 0
    # host i
    pio train ... --num-hosts K --coordinator host0:8476 --process-id i

Per-host ingest sharding: each host reads only its contiguous block of
training rows (:func:`process_row_block`) and contributes it to the
globally-sharded array with :func:`make_global_array` — the analog of the
reference's executor-local partition reads (``JDBCPEvents.scala:31-100``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

_INITIALIZED = False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Topology flags (CLI ``--coordinator/--num-hosts/--process-id`` or
    ``PIO_COORDINATOR/PIO_NUM_HOSTS/PIO_PROCESS_ID`` env — the env path
    mirrors the reference's PIO_* forwarding, Runner.scala:119-121)."""

    coordinator: Optional[str] = None     # "host:port"
    num_hosts: int = 1
    process_id: Optional[int] = None
    local_device_ids: Optional[Tuple[int, ...]] = None

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        ids = os.environ.get("PIO_LOCAL_DEVICE_IDS")
        return cls(
            coordinator=os.environ.get("PIO_COORDINATOR") or None,
            num_hosts=int(os.environ.get("PIO_NUM_HOSTS", "1")),
            process_id=(int(os.environ["PIO_PROCESS_ID"])
                        if "PIO_PROCESS_ID" in os.environ else None),
            local_device_ids=(tuple(int(x) for x in ids.split(","))
                              if ids else None),
        )

    @classmethod
    def from_args(cls, args) -> "DistributedConfig":
        """Build from argparse flags, falling back to the env scheme."""
        env = cls.from_env()
        return cls(
            coordinator=getattr(args, "coordinator", None) or env.coordinator,
            num_hosts=(getattr(args, "num_hosts", None) or env.num_hosts),
            process_id=(getattr(args, "process_id", None)
                        if getattr(args, "process_id", None) is not None
                        else env.process_id),
            local_device_ids=env.local_device_ids,
        )

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1 or self.coordinator is not None


def initialize(config: Optional[DistributedConfig] = None) -> bool:
    """Connect this process to the multi-host runtime.

    Single-process degenerate case (``num_hosts <= 1``, no coordinator):
    no-op, returns False — ``jax.process_count() == 1`` and every mesh
    helper below still works. Multi-host: calls
    ``jax.distributed.initialize`` (idempotent per process) and returns
    True; after it, ``jax.devices()`` is global and ``jax.local_devices()``
    is this host's slice.
    """
    global _INITIALIZED
    config = config or DistributedConfig.from_env()
    if not config.is_multi_host:
        return False
    if _INITIALIZED:
        return True
    if not config.coordinator:
        raise ValueError("--coordinator HOST:PORT is required when "
                         "--num-hosts > 1")
    if config.process_id is None:
        raise ValueError("--process-id is required when --num-hosts > 1 "
                         "(0..num_hosts-1, unique per host)")

    import jax

    jax.distributed.initialize(
        coordinator_address=config.coordinator,
        num_processes=config.num_hosts,
        process_id=config.process_id,
        local_device_ids=config.local_device_ids,
    )
    _INITIALIZED = True
    return True


def shutdown() -> None:
    """Tear down the distributed client (tests / clean exit)."""
    global _INITIALIZED
    if _INITIALIZED:
        import jax

        jax.distributed.shutdown()
        _INITIALIZED = False


def is_primary_host() -> bool:
    """True on the host that owns metadata/model persistence (host 0 —
    the reference's Spark *driver* role).

    Also honors a ``jax.distributed.initialize`` done OUTSIDE this module
    (standard JAX practice): if the distributed client exists, host rank
    decides. Deliberately jax-free in the plain single-process case so
    storage-only workflows never touch a backend."""
    import sys

    if _INITIALIZED:
        return process_index() == 0
    jax = sys.modules.get("jax")
    if jax is None:
        return True
    try:
        from jax._src import distributed as _jax_dist

        if getattr(_jax_dist.global_state, "client", None) is not None:
            return jax.process_index() == 0
    except Exception:  # private-API drift: fall back to primary
        pass
    return True


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def host_aware_mesh(model: int = 1, devices: Optional[Sequence] = None):
    """Global (data × model) mesh with model-axis groups kept WITHIN a
    host, so the per-half-step factor all-gathers of the 2-D ALS layout
    ride ICI while only the data-axis reductions cross DCN (the
    cheap-axis-inside rule of the scaling playbook).

    With one host this degenerates to :func:`mesh_2d` /
    :func:`data_parallel_mesh` over the local devices.
    """
    import numpy as np
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    if model <= 0 or len(devs) % model:
        raise ValueError(
            f"model axis {model} must divide device count {len(devs)}")
    per_host = min(
        sum(1 for d in devs if d.process_index == p)
        for p in {d.process_index for d in devs})
    if model > 1 and per_host % model:
        raise ValueError(
            f"model axis {model} must divide the per-host device count "
            f"{per_host} so model groups stay host-local (otherwise the "
            "factor all-gathers would cross DCN)")
    # order by (host, device) so a reshape keeps model groups host-local
    devs.sort(key=lambda d: (d.process_index, d.id))
    arr = np.asarray(devs).reshape(len(devs) // model, model)
    if model == 1:
        return jax.sharding.Mesh(arr[:, 0], ("data",))
    return jax.sharding.Mesh(arr, ("data", "model"))


def process_row_block(n_rows: int,
                      index: Optional[int] = None,
                      count: Optional[int] = None) -> Tuple[int, int]:
    """Contiguous ``[start, stop)`` row block this host ingests — the
    executor-partition analog of the reference's time-partitioned reads
    (``JDBCPEvents.scala:46-48``). Blocks are balanced to within one row;
    every row belongs to exactly one host."""
    if index is None:
        index = process_index()
    if count is None:
        count = process_count()
    if not 0 <= index < count:
        raise ValueError(f"process index {index} not in [0, {count})")
    base, extra = divmod(n_rows, count)
    start = index * base + min(index, extra)
    stop = start + base + (1 if index < extra else 0)
    return start, stop


def make_global_array(mesh, spec, local_block):
    """Assemble a globally-sharded array from this host's block.

    ``local_block`` is the rows returned by :func:`process_row_block`
    (host-sharded ingest); the result is a single jax.Array sharded per
    ``spec`` over the whole mesh. Works unchanged in the single-process
    case (block == whole array)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local_block)
