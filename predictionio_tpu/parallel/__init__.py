"""Distributed execution: mesh construction + sharded training steps.

The reference's distribution story is Spark RDD partitioning plus MLlib's
block-partitioned ALS shuffles (SURVEY §2.6). The TPU-native answer is a
``jax.sharding.Mesh`` with GSPMD sharding propagation: we annotate input
shardings; XLA inserts the all-gathers/psums over ICI. No NCCL/MPI analog
is needed — collectives are compiled into the program.
"""

from predictionio_tpu.parallel.mesh import data_parallel_mesh, mesh_2d
from predictionio_tpu.parallel.als_sharding import (
    ItemShardLayout,
    contiguous_item_layout,
    density_aware_item_layout,
    train_als_sharded,
    train_als_sharded_2d,
)
from predictionio_tpu.parallel import distributed  # multi-host runtime
from predictionio_tpu.parallel.distributed import (
    DistributedConfig,
    host_aware_mesh,
)
from predictionio_tpu.ops.attention import (  # sequence parallel
    ring_attention,
    ulysses_attention,
)

__all__ = ["data_parallel_mesh", "mesh_2d", "train_als_sharded",
           "train_als_sharded_2d", "ring_attention", "ulysses_attention",
           "distributed", "DistributedConfig", "host_aware_mesh",
           "ItemShardLayout", "density_aware_item_layout",
           "contiguous_item_layout"]
