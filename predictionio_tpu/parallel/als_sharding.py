"""Sharded ALS training over a device mesh.

MLlib ALS distributes by block-partitioning both factor matrices and
shuffling ratings between executors every half-step (invoked from
``examples/.../ALSAlgorithm.scala:64-71``). The TPU-native replacement
(ALX layout): shard the PADDED RATING TABLES row-wise over the mesh's
``data`` axis so each device solves its slice of users (then items);
factor matrices are kept replicated and rebuilt each half-step — XLA's
sharding propagation turns the per-slice solves + gathers into
all-gather/psum collectives over ICI, replacing the Spark shuffle.

Memory note: replicated factors cost ``(N+M) * R * 4`` bytes per device —
fine through MovieLens-20M (~165 MB at R=128). A 2-D ``(data, model)``
factor-sharded variant is the next scale step (mesh_2d is ready for it).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.ops.als import (
    ALSParams,
    PaddedRatings,
    _als_iterations_impl,
    init_factors,
)


def _pad_rows_to(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad the leading dim to n rows (zeros = no-op ratings)."""
    if arr.shape[0] == n:
        return arr
    pad = np.zeros((n - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def train_als_sharded(user_side: PaddedRatings, item_side: PaddedRatings,
                      params: ALSParams, mesh,
                      dtype=None) -> Tuple[np.ndarray, np.ndarray]:
    """Train with rating tables sharded over ``mesh`` axis 'data'.

    Produces the same numerics as :func:`~predictionio_tpu.ops.als.train_als`
    (same init, same solves) — verified by tests on the virtual CPU mesh.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = mesh.devices.size
    X, Y = init_factors(user_side.n_rows, user_side.n_cols, params.rank,
                        params.seed, dtype)

    # Pad row counts to a multiple of the mesh size so shards are even.
    n_u = -(-user_side.n_rows // n_dev) * n_dev
    n_i = -(-item_side.n_rows // n_dev) * n_dev
    u_cols = _pad_rows_to(user_side.cols, n_u)
    u_w = _pad_rows_to(user_side.weights, n_u)
    u_m = _pad_rows_to(user_side.mask, n_u)
    i_cols = _pad_rows_to(item_side.cols, n_i)
    i_w = _pad_rows_to(item_side.weights, n_i)
    i_m = _pad_rows_to(item_side.mask, n_i)
    X = _pad_rows_to(np.asarray(X), n_u)
    Y = _pad_rows_to(np.asarray(Y), n_i)

    row_sharded = NamedSharding(mesh, P("data", None))
    replicated = NamedSharding(mesh, P(None, None))

    u_cols = jax.device_put(jnp.asarray(u_cols), row_sharded)
    u_w = jax.device_put(jnp.asarray(u_w), row_sharded)
    u_m = jax.device_put(jnp.asarray(u_m), row_sharded)
    i_cols = jax.device_put(jnp.asarray(i_cols), row_sharded)
    i_w = jax.device_put(jnp.asarray(i_w), row_sharded)
    i_m = jax.device_put(jnp.asarray(i_m), row_sharded)
    X = jax.device_put(jnp.asarray(X), replicated)
    Y = jax.device_put(jnp.asarray(Y), replicated)

    step = jax.jit(
        _als_iterations_impl,
        static_argnames=("lam", "alpha", "implicit", "num_iterations"),
        # Keep factor outputs replicated: each half-step's solve output is
        # row-sharded; forcing replication here makes XLA all-gather it
        # before the next gather-by-index — the ICI analog of MLlib's
        # factor shuffle.
        out_shardings=(replicated, replicated),
    )
    X, Y = step(X, Y, u_cols, u_w, u_m, i_cols, i_w, i_m,
                lam=float(params.lambda_), alpha=float(params.alpha),
                implicit=bool(params.implicit_prefs),
                num_iterations=int(params.num_iterations))
    return (np.asarray(X)[:user_side.n_rows],
            np.asarray(Y)[:item_side.n_rows])


def sharded_train_step(mesh, rank: int, params: Optional[ALSParams] = None):
    """Return (jitted_step_fn, sharding_specs) for ONE alternating
    iteration — the unit the multichip dry-run compiles and executes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = params or ALSParams(rank=rank, num_iterations=1)
    row_sharded = NamedSharding(mesh, P("data", None))
    replicated = NamedSharding(mesh, P(None, None))

    fn = jax.jit(
        _als_iterations_impl,
        static_argnames=("lam", "alpha", "implicit", "num_iterations"),
        out_shardings=(replicated, replicated),
    )

    def run(X, Y, u_cols, u_w, u_m, i_cols, i_w, i_m):
        import jax.numpy as jnp

        put = jax.device_put
        return fn(put(jnp.asarray(X), replicated),
                  put(jnp.asarray(Y), replicated),
                  put(jnp.asarray(u_cols), row_sharded),
                  put(jnp.asarray(u_w), row_sharded),
                  put(jnp.asarray(u_m), row_sharded),
                  put(jnp.asarray(i_cols), row_sharded),
                  put(jnp.asarray(i_w), row_sharded),
                  put(jnp.asarray(i_m), row_sharded),
                  lam=float(params.lambda_), alpha=float(params.alpha),
                  implicit=bool(params.implicit_prefs),
                  num_iterations=1)

    return run, {"rows": row_sharded, "factors": replicated}
