"""Sharded ALS training over a device mesh.

MLlib ALS distributes by block-partitioning both factor matrices and
shuffling ratings between executors every half-step (invoked from
``examples/.../ALSAlgorithm.scala:64-71``). The TPU-native replacement
(ALX layout): shard the PADDED RATING TABLES row-wise over the mesh's
``data`` axis so each device solves its slice of users (then items);
factor matrices are kept replicated and rebuilt each half-step — XLA's
sharding propagation turns the per-slice solves + gathers into
all-gather/psum collectives over ICI, replacing the Spark shuffle.

Memory note: replicated factors cost ``(N+M) * R * 4`` bytes per device —
fine through MovieLens-20M (~165 MB at R=128). Past that,
``train_als_sharded_2d`` shards the factor matrices over the mesh's
``model`` axis (per-device factor memory drops by the model-axis size;
one transient all-gather per half-step over ICI — the ALX layout).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger("predictionio_tpu.als_sharding")

from predictionio_tpu.ops.als import (
    ALSParams,
    BucketedRatings,
    PaddedRatings,
    RatingsBucket,
    _als_iterations_bucketed_impl,
    _als_iterations_impl,
    _als_precision_mode,
    _maybe_checkpointer,
    _objective_pack,
    _objective_statics,
    _spd_solver_mode,
    _train_telemetry_enabled,
    _uniform_objective_bucket,
    checkpoint_layout_bucketed,
    checkpoint_layout_uniform,
    factor_dtype,
    init_policy_factors,
)


# ---------------------------------------------------------------------------
# Density-aware item sharding (the ALX layout step the live plane uses)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ItemShardLayout:
    """How the item axis of a mesh-sharded factor store is laid out.

    ``perm[pos] -> item id`` (or -1 for an empty pad slot) over
    ``n_shards * cap`` contiguous positions — shard ``s`` owns positions
    ``[s*cap, (s+1)*cap)``; ``inv[item] -> pos`` is its inverse. The
    layout is part of the MODEL artifact: serving permutes the item
    factor rows into it, fold-in reads the item store back through it,
    and top-k results translate back to item ids on host — so every
    consumer sees one consistent placement (the contiguous-span
    alternative hot-spots the power-law head onto shard 0)."""

    perm: np.ndarray            # int64 [n_shards * cap], -1 = pad slot
    inv: np.ndarray             # int64 [n_items], item id -> position
    n_shards: int
    n_items: int
    counts_per_shard: np.ndarray  # int64 [n_shards] interaction mass

    @property
    def n_positions(self) -> int:
        return int(self.perm.shape[0])

    @property
    def cap(self) -> int:
        return self.n_positions // self.n_shards

    @property
    def items_per_shard(self) -> np.ndarray:
        """Real items each shard holds (pad slots excluded)."""
        return (self.perm.reshape(self.n_shards, self.cap)
                >= 0).sum(axis=1)

    def valid_mask(self) -> np.ndarray:
        """float32 [n_positions]: 1.0 where the position holds a real
        item — the on-device validity row the sharded top-k masks by
        (replaces the contiguous layout's ``index < n_items`` test)."""
        return (self.perm >= 0).astype(np.float32)

    def balance_report(self) -> Dict[str, Any]:
        """Interaction-mass balance across shards, with the contiguous
        baseline's imbalance alongside — the artifact line that shows
        what the bin-pack bought on power-law data."""
        c = self.counts_per_shard.astype(np.float64)
        mean = float(c.mean()) if len(c) else 0.0
        return {
            "nShards": int(self.n_shards),
            "itemsPerShard": [int(v) for v in self.items_per_shard],
            "interactionsPerShard": [int(v) for v in
                                     self.counts_per_shard],
            "maxOverMeanInteractions": round(
                float(c.max()) / mean, 4) if mean > 0 else None,
        }

    def to_json(self) -> Dict[str, Any]:
        return {"perm": self.perm.tolist(), "nShards": int(self.n_shards),
                "nItems": int(self.n_items),
                "countsPerShard": self.counts_per_shard.tolist()}

    @classmethod
    def from_json(cls, blob: Dict[str, Any]) -> "ItemShardLayout":
        perm = np.asarray(blob["perm"], dtype=np.int64)
        n_items = int(blob["nItems"])
        inv = np.full(n_items, -1, dtype=np.int64)
        real = perm >= 0
        inv[perm[real]] = np.flatnonzero(real)
        return cls(perm, inv, int(blob["nShards"]), n_items,
                   np.asarray(blob["countsPerShard"], dtype=np.int64))


def _layout_from_assignment(shards, counts: np.ndarray, n_shards: int,
                            cap: int) -> ItemShardLayout:
    n_items = int(len(counts))
    perm = np.full(n_shards * cap, -1, dtype=np.int64)
    mass = np.zeros(n_shards, dtype=np.int64)
    for s, items in enumerate(shards):
        items = np.sort(np.asarray(items, dtype=np.int64))
        perm[s * cap:s * cap + len(items)] = items
        mass[s] = int(counts[items].sum()) if len(items) else 0
    inv = np.full(n_items, -1, dtype=np.int64)
    real = perm >= 0
    inv[perm[real]] = np.flatnonzero(real)
    return ItemShardLayout(perm, inv, n_shards, n_items, mass)


def contiguous_item_layout(n_items: int, n_shards: int,
                           counts: Optional[np.ndarray] = None,
                           cap_multiple: int = 8) -> ItemShardLayout:
    """The span layout (items ``[s*cap, (s+1)*cap)`` on shard ``s``) —
    what density-aware sharding replaces, kept for stores without
    interaction counts and as the balance baseline."""
    n_shards = max(1, int(n_shards))
    cap = -(-max(int(n_items), 1) // n_shards)
    cap = -(-cap // cap_multiple) * cap_multiple
    if counts is None:
        counts = np.zeros(n_items, dtype=np.int64)
    ids = np.arange(n_items, dtype=np.int64)
    shards = [ids[s * cap:(s + 1) * cap] for s in range(n_shards)]
    return _layout_from_assignment(shards, np.asarray(counts), n_shards,
                                   cap)


def density_aware_item_layout(counts, n_shards: int,
                              cap_multiple: int = 8) -> ItemShardLayout:
    """Assign items to shards by interaction count: greedy bin-pack
    (heaviest item first onto the lightest shard with free capacity),
    so the power-law head spreads instead of hot-spotting shard 0 —
    the ALX density-aware placement. Capacity-bounded: every shard
    holds at most ``cap`` items, so the factor table still shards
    evenly over the mesh axis; within a shard items sit in ascending
    id order (deterministic layout for a given count vector)."""
    counts = np.asarray(counts, dtype=np.int64)
    n_items = int(counts.shape[0])
    n_shards = max(1, int(n_shards))
    cap = -(-max(n_items, 1) // n_shards)
    cap = -(-cap // cap_multiple) * cap_multiple
    # heaviest first; ties broken by item id for determinism
    order = np.lexsort((np.arange(n_items), -counts))
    heap = [(0, s) for s in range(n_shards)]  # (mass, shard)
    heapq.heapify(heap)
    shards = [[] for _ in range(n_shards)]
    for item in order:
        while True:
            mass, s = heapq.heappop(heap)
            if len(shards[s]) < cap:
                break
            # full shard: leaves the heap for good (total capacity
            # >= n_items, so the pop can never empty the heap early)
        shards[s].append(int(item))
        heapq.heappush(heap, (mass + int(counts[item]), s))
    return _layout_from_assignment(shards, counts, n_shards, cap)


def _multihost_checkpointer(layout, params, solver, precision, dtype,
                            multi_host: bool):
    """The crash-safe checkpointer for a sharded trainer, or None.
    Multi-host runs keep the single-scan path (a per-chunk DCN gather
    + host-0-only writes is ROADMAP item-2 territory) — but NEVER
    silently: an operator who passed the crash-safe knobs must know
    they are not protected."""
    if not multi_host:
        return _maybe_checkpointer(layout, params, solver, precision,
                                   dtype)
    if os.environ.get("PIO_CHECKPOINT_DIR", "").strip():
        logger.warning(
            "checkpointing (PIO_CHECKPOINT_DIR) is not supported on "
            "multi-host meshes yet: this training runs as ONE "
            "uninterruptible scan and writes NO checkpoints; --resume "
            "will find nothing from this run")
    return None


def _pad_rows_to(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad the leading dim to n rows (zeros = no-op ratings)."""
    if arr.shape[0] == n:
        return arr
    pad = np.zeros((n - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def _jit_step(mesh, factor_spec):
    """The production jitted iteration program: factor outputs pinned to
    ``factor_spec`` between iterations; XLA inserts the collectives
    (all-gather before each index-gather — the ICI analog of MLlib's
    factor shuffle). The X/Y carries are donated — input and output
    shardings match, so steady-state steps update the factor shards in
    place instead of copying them per dispatch."""
    import jax
    from jax.sharding import NamedSharding

    factor_sharded = NamedSharding(mesh, factor_spec)
    return jax.jit(
        _als_iterations_impl,
        static_argnames=("lam", "alpha", "implicit", "num_iterations",
                         "solver", "precision", "refine"),
        out_shardings=(factor_sharded, factor_sharded),
        donate_argnums=(0, 1),
    )


def _train_sharded(user_side: PaddedRatings, item_side: PaddedRatings,
                   params: ALSParams, mesh, row_divisor: int,
                   factor_spec, dtype,
                   gather: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Shared sharded-training body: pad rows to ``row_divisor``, shard
    rating tables over 'data', place factors per ``factor_spec``, run the
    full iteration scan, slice padding back off."""
    import jax

    if not isinstance(user_side, PaddedRatings):
        raise TypeError(
            "this ALS flavor trains uniform PaddedRatings tables; for "
            "length-bucketed sides use train_als_bucketed_sharded (or "
            "the default ALSAlgorithm via train_als_auto), or set "
            "bucketed=False on the preparator")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    precision = _als_precision_mode(params)  # resolved per call
    X, Y = init_policy_factors(user_side.n_rows, user_side.n_cols,
                               params.rank, params.seed, dtype, precision)
    n_u = -(-user_side.n_rows // row_divisor) * row_divisor
    n_i = -(-item_side.n_rows // row_divisor) * row_divisor

    row_sharded = NamedSharding(mesh, P("data", None))
    factor_sharded = NamedSharding(mesh, factor_spec)
    put = jax.device_put
    # keyed on the MESH, not jax.process_count(): a local mesh inside a
    # distributed runtime must still take the single-host placement path
    multi_host = len({d.process_index for d in mesh.devices.flat}) > 1

    def place_rows(a, n):
        """Rating-table rows, sharded over 'data'. Multi-host: each host
        contributes only its contiguous row block (host-sharded ingest,
        parallel/distributed.py); single-host: plain device_put."""
        a = _pad_rows_to(a, n)
        if multi_host:
            from predictionio_tpu.parallel import distributed

            start, stop = distributed.process_row_block(n)
            return distributed.make_global_array(mesh, P("data", None),
                                                 a[start:stop])
        return put(jnp.asarray(a), row_sharded)

    def place_factor(a, n):
        """Factor matrices: replicated or model-axis sharded. With
        host_aware_mesh's host-local model groups every host holds all
        model positions, so its process-local data is the full matrix."""
        a = _pad_rows_to(np.asarray(a), n)
        if multi_host:
            from predictionio_tpu.parallel import distributed

            return distributed.make_global_array(mesh, factor_spec, a)
        return put(jnp.asarray(a), factor_sharded)

    def rows(side, n):
        return [place_rows(a, n) for a in (side.cols, side.weights,
                                           side.mask)]

    u_cols, u_w, u_m = rows(user_side, n_u)
    i_cols, i_w, i_m = rows(item_side, n_i)
    X = place_factor(X, n_u)
    Y = place_factor(Y, n_i)

    step = _jit_step(mesh, factor_spec)
    kw = dict(lam=float(params.lambda_), alpha=float(params.alpha),
              implicit=bool(params.implicit_prefs),
              solver=_spd_solver_mode(),  # resolved per call
              precision=precision, refine=bool(params.solve_refine))

    def run_iters(Xc, Yc, n):
        return step(Xc, Yc, u_cols, u_w, u_m, i_cols, i_w, i_m,
                    num_iterations=int(n), **kw)

    # crash-safe lane: single-host sharded runs checkpoint between
    # chunks (np.asarray gathers the factor shards)
    ckpt = _multihost_checkpointer(
        checkpoint_layout_uniform(user_side, item_side), params,
        kw["solver"], precision, dtype, multi_host)
    if ckpt is None:
        X, Y = run_iters(X, Y, int(params.num_iterations))
    else:
        from predictionio_tpu.workflow import checkpoint as _checkpoint

        fdt = X.dtype
        objective = None
        if _train_telemetry_enabled():
            # same jitted objective program as the single-device lane;
            # the sharded tables flow through jit and GSPMD inserts the
            # psum merges (the pack stays one replicated [3] scalar)
            obj_bucket = _uniform_objective_bucket(u_cols, u_w, u_m, n_u)
            obj_kw = _objective_statics(params)

            def objective(Xc, Yc):
                return _objective_pack(Xc, Yc, (obj_bucket,), **obj_kw)

        X, Y = _checkpoint.run_chunked(
            run_iters, X, Y, int(params.num_iterations), ckpt,
            to_host=lambda a: np.asarray(a, dtype=np.float32),
            from_host=lambda a: put(jnp.asarray(a, dtype=fdt),
                                    factor_sharded),
            objective=objective)
    if not gather:
        # PAlgorithm path: factors STAY sharded in HBM (padded to n_u/n_i
        # rows, bf16 under the bf16 policy); the caller serves from them
        # directly (ops/serving.py accepts bf16 factor Arrays)
        return X, Y
    if multi_host:
        # factors are needed host-side on every host (model persistence,
        # serving); gather across processes over DCN
        from jax.experimental import multihost_utils

        X = multihost_utils.process_allgather(X, tiled=True)
        Y = multihost_utils.process_allgather(Y, tiled=True)
    # host factors always land fp32 (see ops.als.train_als)
    return (np.asarray(X, dtype=np.float32)[:user_side.n_rows],
            np.asarray(Y, dtype=np.float32)[:item_side.n_rows])


def train_als_sharded(user_side: PaddedRatings, item_side: PaddedRatings,
                      params: ALSParams, mesh,
                      dtype=None) -> Tuple[np.ndarray, np.ndarray]:
    """Train with rating tables sharded over ``mesh`` axis 'data' and
    factor matrices replicated.

    Produces the same numerics as :func:`~predictionio_tpu.ops.als.train_als`
    (same init, same solves) — verified by tests on the virtual CPU mesh.
    """
    from jax.sharding import PartitionSpec as P

    return _train_sharded(user_side, item_side, params, mesh,
                          row_divisor=mesh.devices.size,
                          factor_spec=P(None, None), dtype=dtype)


def train_als_sharded_2d(user_side: PaddedRatings, item_side: PaddedRatings,
                         params: ALSParams, mesh,
                         dtype=None) -> Tuple[np.ndarray, np.ndarray]:
    """2-D (data x model) sharded training: rating tables row-sharded over
    'data', FACTOR MATRICES row-sharded over 'model'.

    This is the scale step beyond replicated factors (module docstring):
    each device stores only ``rows/model_size`` of each factor matrix in
    HBM; GSPMD all-gathers the fixed side transiently for the gather-by-
    index of each half-step and scatters the solve output back to its
    shard — factor memory per device drops by the model-axis size at the
    cost of one all-gather per half-step over ICI (the ALX layout).
    Numerics identical to :func:`~predictionio_tpu.ops.als.train_als`.
    Rows pad to a multiple of data*model so BOTH shardings split evenly.
    """
    from jax.sharding import PartitionSpec as P

    return _train_sharded(user_side, item_side, params, mesh,
                          row_divisor=mesh.shape["data"] * mesh.shape["model"],
                          factor_spec=P("model", None), dtype=dtype)


def train_als_device(user_side, item_side,
                     params: ALSParams, mesh=None, dtype=None):
    """Train and KEEP the factors sharded in HBM — the PAlgorithm flavor
    (PAlgorithm.scala:44-126: the model lives distributed; nothing is
    gathered to host). Accepts uniform :class:`PaddedRatings` or
    length-bucketed :class:`BucketedRatings` sides.

    Returns ``(X, Y)`` as jax Arrays padded to the mesh divisor — on a
    2-D mesh they are row-sharded over the 'model' axis (each device
    stores 1/model of each factor matrix), on a 1-D mesh replicated.
    Serve them with :class:`predictionio_tpu.ops.serving.DeviceTopK`,
    passing the true n_users/n_items as the index bounds.
    """
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        from predictionio_tpu.parallel.distributed import host_aware_mesh

        import jax

        n = len(jax.devices())
        mesh = host_aware_mesh(model=2 if (n % 2 == 0 and n >= 4) else 1)
    if "model" in mesh.axis_names:
        divisor = mesh.shape["data"] * mesh.shape["model"]
        spec = P("model", None)
    else:
        divisor = mesh.devices.size
        spec = P(None, None)
    if isinstance(user_side, BucketedRatings):
        # the scale combination: bucketed solves + factors kept in HBM
        # (model-sharded on a 2-D mesh); note the returned Arrays are
        # NOT row-padded — bucketed training sizes them exactly
        return train_als_bucketed_sharded(
            user_side, item_side, params, mesh, dtype=dtype,
            factor_spec=spec, gather=False)
    return _train_sharded(user_side, item_side, params, mesh,
                          row_divisor=divisor, factor_spec=spec,
                          dtype=dtype, gather=False)


def _pad_bucket_rows(b: RatingsBucket, multiple: int,
                     sentinel: int) -> RatingsBucket:
    """Pad a bucket's row count to ``multiple`` with sentinel-id empty
    rows (dropped by the device scatter) so the table shards evenly."""
    B = int(np.asarray(b.cols).shape[0])
    pad = (-B) % multiple
    if pad == 0:
        return b

    def z(a):
        a = np.asarray(a)
        return np.concatenate(
            [a, np.zeros((pad, a.shape[1]), dtype=a.dtype)])
    rid = np.concatenate([np.asarray(b.row_ids),
                          np.full(pad, sentinel, dtype=np.int32)])
    return RatingsBucket(rid, z(b.cols), z(b.weights), z(b.mask))


def train_als_bucketed_sharded(user_side: BucketedRatings,
                               item_side: BucketedRatings,
                               params: ALSParams, mesh, dtype=None,
                               factor_spec=None, gather: bool = True
                               ) -> Tuple:
    """Length-bucketed training over a device mesh.

    Every bucket's table is row-sharded over the mesh's ``data`` axis
    (rows padded to a lane-friendly multiple of the axis size with
    sentinel ids). By default the factor matrices stay replicated, so
    each device's per-bucket solves scatter into its replica and XLA
    merges the disjoint scatters with one psum per half-step — the
    collective analog of MLlib's factor shuffle, at bucketed occupancy
    instead of longest-row padding. ``factor_spec`` (e.g.
    ``P("model", None)``) shards the factor matrices instead (the ALX
    layout's memory step; factor rows pad to the sharded-dim divisor);
    ``gather=False`` returns the factors as device Arrays in that
    (row-padded) placement — the PAlgorithm flavor where the model
    never lands on host."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    ndev = int(mesh.shape.get("data", 1))
    rows_sharded = NamedSharding(mesh, P("data", None))
    ids_sharded = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, factor_spec or P(None, None))
    put = jax.device_put
    multi_host = len({d.process_index for d in mesh.devices.flat}) > 1

    def place_arr(a, sharding, spec):
        """Single-host: plain device_put; multi-host: this host
        contributes its contiguous row block (host-sharded ingest,
        parallel/distributed.py)."""
        if multi_host:
            from predictionio_tpu.parallel import distributed

            start, stop = distributed.process_row_block(a.shape[0])
            return distributed.make_global_array(mesh, spec,
                                                 np.asarray(a)[start:stop])
        return put(jnp.asarray(a), sharding)

    def place(side: BucketedRatings):
        out = []
        for b in side.buckets:
            b = _pad_bucket_rows(b, 8 * ndev, side.n_rows)
            out.append((place_arr(b.row_ids, ids_sharded, P("data")),
                        place_arr(b.cols, rows_sharded, P("data", None)),
                        place_arr(b.weights, rows_sharded,
                                  P("data", None)),
                        place_arr(b.mask, rows_sharded, P("data", None))))
        return tuple(out)

    precision = _als_precision_mode(params)  # resolved per call
    X, Y = init_policy_factors(user_side.n_rows, item_side.n_rows,
                               params.rank, params.seed, dtype, precision)
    # a sharded factor dim must split evenly: pad rows (with ZEROS — a
    # random-init pad row would pollute the first shared Gram term) to
    # the dim-0 axis product; pad rows are never scattered into by a
    # real bucket row and serving masks them via n_users/n_items
    dim0 = (factor_spec or P(None, None))[0]
    names = (dim0,) if isinstance(dim0, str) else tuple(dim0 or ())
    divisor = 1
    for a in names:
        divisor *= int(mesh.shape[a])
    n_u_pad = -(-user_side.n_rows // divisor) * divisor
    n_i_pad = -(-item_side.n_rows // divisor) * divisor
    X = _pad_rows_to(np.asarray(X), n_u_pad)
    Y = _pad_rows_to(np.asarray(Y), n_i_pad)
    if multi_host:
        from predictionio_tpu.parallel import distributed

        spec = factor_spec or P(None, None)
        X = distributed.make_global_array(mesh, spec, X)
        Y = distributed.make_global_array(mesh, spec, Y)
    else:
        X, Y = put(jnp.asarray(X), repl), put(jnp.asarray(Y), repl)
    fn = jax.jit(
        _als_iterations_bucketed_impl,
        static_argnames=("lam", "alpha", "implicit", "num_iterations",
                         "slot_budget", "solver", "precision", "refine"),
        out_shardings=(repl, repl),
        donate_argnums=(0, 1))
    u_t, i_t = place(user_side), place(item_side)
    kw = dict(lam=float(params.lambda_), alpha=float(params.alpha),
              implicit=bool(params.implicit_prefs),
              slot_budget=None if not params.bucket_slot_budget
              else int(params.bucket_slot_budget),
              solver=_spd_solver_mode(),  # resolved per call
              precision=precision, refine=bool(params.solve_refine))

    def run_iters(Xc, Yc, n):
        return fn(Xc, Yc, u_t, i_t, num_iterations=int(n), **kw)

    # crash-safe lane (see _multihost_checkpointer: single-host only)
    ckpt = _multihost_checkpointer(
        checkpoint_layout_bucketed(user_side, item_side), params,
        kw["solver"], precision, dtype, multi_host)
    if ckpt is None:
        X, Y = run_iters(X, Y, int(params.num_iterations))
    else:
        from predictionio_tpu.workflow import checkpoint as _checkpoint

        fdt = X.dtype
        objective = None
        if _train_telemetry_enabled():
            # closure over the PLACED bucket tuples (see _objective_pack:
            # sharded inputs through the same jitted program)
            obj_kw = _objective_statics(params)

            def objective(Xc, Yc):
                return _objective_pack(Xc, Yc, u_t, **obj_kw)

        X, Y = _checkpoint.run_chunked(
            run_iters, X, Y, int(params.num_iterations), ckpt,
            to_host=lambda a: np.asarray(a, dtype=np.float32),
            from_host=lambda a: put(jnp.asarray(a, dtype=fdt), repl),
            objective=objective)
    if not gather:
        # PAlgorithm flavor: factors stay in HBM in their sharded
        # placement (rows padded to the factor divisor, bf16 under the
        # bf16 policy); serve via ops.serving.DeviceTopK with the true
        # n_users/n_items bounds
        return X, Y
    # host factors always land fp32 (see ops.als.train_als)
    return (np.asarray(X, dtype=np.float32)[:user_side.n_rows],
            np.asarray(Y, dtype=np.float32)[:item_side.n_rows])


def train_als_auto(user_side, item_side, params: ALSParams, dtype=None
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Topology-aware trainer — what the templates call. Accepts either
    uniform :class:`PaddedRatings` or length-bucketed
    :class:`BucketedRatings` sides (the Preparator's choice).

    Multi-host runtime (``pio train --num-hosts K``): a global host-aware
    mesh so all hosts train ONE collective program over DCN+ICI.
    Single host, multiple devices: data-parallel over the local mesh.
    One device: the plain jitted path. Numerics are identical across all
    three (same init, same solves; tested on the virtual mesh).
    """
    import jax

    from predictionio_tpu.ops.als import train_als, train_als_bucketed

    bucketed = isinstance(user_side, BucketedRatings)
    if jax.process_count() > 1:
        from predictionio_tpu.parallel import distributed

        mesh = distributed.host_aware_mesh()
        if bucketed:
            return train_als_bucketed_sharded(user_side, item_side,
                                              params, mesh, dtype=dtype)
        return train_als_sharded(user_side, item_side, params, mesh,
                                 dtype=dtype)
    from predictionio_tpu.parallel.mesh import data_parallel_mesh

    if len(jax.devices()) > 1:
        if bucketed:
            return train_als_bucketed_sharded(
                user_side, item_side, params, data_parallel_mesh(),
                dtype=dtype)
        return train_als_sharded(user_side, item_side, params,
                                 data_parallel_mesh(), dtype=dtype)
    if bucketed:
        return train_als_bucketed(user_side, item_side, params,
                                  dtype=dtype)
    return train_als(user_side, item_side, params, dtype=dtype)


def sharded_train_step(mesh, rank: int, params: Optional[ALSParams] = None):
    """Return (jitted_step_fn, sharding_specs) for ONE alternating
    iteration — the unit the multichip dry-run compiles and executes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = params or ALSParams(rank=rank, num_iterations=1)
    row_sharded = NamedSharding(mesh, P("data", None))
    replicated = NamedSharding(mesh, P(None, None))

    fn = jax.jit(
        _als_iterations_impl,
        static_argnames=("lam", "alpha", "implicit", "num_iterations",
                         "solver", "precision", "refine"),
        out_shardings=(replicated, replicated),
        donate_argnums=(0, 1),
    )

    def run(X, Y, u_cols, u_w, u_m, i_cols, i_w, i_m):
        import jax.numpy as jnp

        put = jax.device_put
        precision = _als_precision_mode(params)  # resolved per call
        # the caller's host factors enter in the policy's storage dtype
        # — under bf16 the step must actually exercise the half-width
        # gather, not a mongrel fp32-store/bf16-weights lane
        fdt = factor_dtype(precision)
        return fn(put(jnp.asarray(X, dtype=fdt), replicated),
                  put(jnp.asarray(Y, dtype=fdt), replicated),
                  put(jnp.asarray(u_cols), row_sharded),
                  put(jnp.asarray(u_w), row_sharded),
                  put(jnp.asarray(u_m), row_sharded),
                  put(jnp.asarray(i_cols), row_sharded),
                  put(jnp.asarray(i_w), row_sharded),
                  put(jnp.asarray(i_m), row_sharded),
                  lam=float(params.lambda_), alpha=float(params.alpha),
                  implicit=bool(params.implicit_prefs),
                  num_iterations=1,
                  solver=_spd_solver_mode(),  # resolved per call
                  precision=precision,
                  refine=bool(params.solve_refine))

    return run, {"rows": row_sharded, "factors": replicated}
