"""Mesh construction helpers.

Replaces the reference's Spark-cluster topology (executors over netty,
SURVEY §2.6 comm-backend row) with explicit jax device meshes. Axis
convention: ``data`` shards batch/rows, ``model`` shards factor/feature
dimensions.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


def shard_spans(n: int, parts: int) -> List[Tuple[int, int]]:
    """Split ``range(n)`` into at most ``parts`` balanced contiguous
    ``(start, stop)`` spans, never emitting an empty span — the
    map-over-shards index math (the DrJAX idiom: a fixed partition of
    the workload mapped over devices/chunks). Used by the
    batch-prediction chunker (``--query-partitions``) and reusable for
    per-device work assignment."""
    if n <= 0:
        return []
    parts = max(1, min(int(parts), n))
    base, rem = divmod(n, parts)
    spans: List[Tuple[int, int]] = []
    start = 0
    for i in range(parts):
        stop = start + base + (1 if i < rem else 0)
        spans.append((start, stop))
        start = stop
    return spans


def data_parallel_mesh(n_devices: Optional[int] = None,
                       devices: Optional[Sequence] = None):
    """1-D mesh over the first ``n_devices`` devices, axis name 'data'."""
    import numpy as np
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.asarray(devs), ("data",))


def mesh_2d(data: int, model: int, devices: Optional[Sequence] = None):
    """2-D (data × model) mesh for model-parallel factor sharding."""
    import numpy as np
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    need = data * model
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return jax.sharding.Mesh(
        np.asarray(devs[:need]).reshape(data, model), ("data", "model"))
