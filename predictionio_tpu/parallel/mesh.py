"""Mesh construction helpers.

Replaces the reference's Spark-cluster topology (executors over netty,
SURVEY §2.6 comm-backend row) with explicit jax device meshes. Axis
convention: ``data`` shards batch/rows, ``model`` shards factor/feature
dimensions.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple


def data_parallel_mesh(n_devices: Optional[int] = None,
                       devices: Optional[Sequence] = None):
    """1-D mesh over the first ``n_devices`` devices, axis name 'data'."""
    import numpy as np
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.asarray(devs), ("data",))


def mesh_2d(data: int, model: int, devices: Optional[Sequence] = None):
    """2-D (data × model) mesh for model-parallel factor sharding."""
    import numpy as np
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    need = data * model
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return jax.sharding.Mesh(
        np.asarray(devs[:need]).reshape(data, model), ("data", "model"))
