"""Server access-key authentication.

Parity: ``KeyAuthentication.scala:33-56`` — the dashboard (and optionally
other daemons) require a server-level access key configured in a file,
matched against the ``accessKey`` query parameter of every request. An
empty/absent configured key means auth is disabled (open server), which is
the behavior the reference gets from a blank ``server.conf`` template.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Mapping, Optional, Sequence

DEFAULT_CONFIG_FILE = "server.json"
ACCESS_KEY_PARAM = "accessKey"  # ServerKey.param


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """The ``server.conf`` analog (io.prediction.server.* keys).

    JSON file shape::

        {"accessKey": "...",
         "ssl": {"certfile": "server.pem", "keyfile": "key.pem",
                 "password": null}}
    """

    access_key: str = ""
    ssl_certfile: Optional[str] = None
    ssl_keyfile: Optional[str] = None
    ssl_password: Optional[str] = None

    @classmethod
    def load(cls, path: Optional[str] = None) -> "ServerConfig":
        """Load from ``path`` (or ``$PIO_SERVER_CONFIG`` or ./server.json);
        missing file -> defaults (open server, no TLS)."""
        path = path or os.environ.get("PIO_SERVER_CONFIG",
                                      DEFAULT_CONFIG_FILE)
        if not os.path.exists(path):
            return cls()
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        ssl_cfg = raw.get("ssl") or {}
        return cls(
            access_key=str(raw.get("accessKey", "") or ""),
            ssl_certfile=ssl_cfg.get("certfile"),
            ssl_keyfile=ssl_cfg.get("keyfile"),
            ssl_password=ssl_cfg.get("password"),
        )


class KeyAuthentication:
    """Request authentication against the configured server key."""

    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()

    @property
    def enabled(self) -> bool:
        return bool(self.config.access_key)

    def authenticate(self, params: Mapping[str, Sequence[str]]) -> bool:
        """True iff auth is disabled or the ``accessKey`` query parameter
        matches (KeyAuthentication.scala:40-55)."""
        if not self.enabled:
            return True
        passed = params.get(ACCESS_KEY_PARAM, [])
        return bool(passed) and passed[0] == self.config.access_key
