"""Shared server infrastructure: access-key auth + TLS configuration.

Parity targets: ``common/.../authentication/KeyAuthentication.scala:33-56``
(server access key loaded from ``server.conf``, checked against the
``accessKey`` query parameter) and
``common/.../configuration/SSLConfiguration.scala:28-72`` (JKS keystore ->
TLS context for the spray servers). The JVM pieces map to their Python
equivalents: typesafe-config ``server.conf`` becomes a JSON ``server.json``,
the JKS keystore becomes PEM cert/key files loaded into ``ssl.SSLContext``.
"""

from predictionio_tpu.common.auth import KeyAuthentication, ServerConfig
from predictionio_tpu.common.ssl_config import SSLConfiguration

__all__ = ["KeyAuthentication", "ServerConfig", "SSLConfiguration"]
