"""TLS context construction for the HTTP daemons.

Parity: ``SSLConfiguration.scala:28-72`` — the reference loads a JKS
keystore named in ``server.conf`` and builds a TLS context for spray's
HTTPS binding. Here the PEM cert/key files named in ``server.json``
build an ``ssl.SSLContext``; any server's listening socket can be wrapped
with it (``wrap_server``).
"""

from __future__ import annotations

import ssl
from typing import Optional

from predictionio_tpu.common.auth import ServerConfig


class SSLConfiguration:
    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()

    @property
    def enabled(self) -> bool:
        return bool(self.config.ssl_certfile)

    def ssl_context(self) -> ssl.SSLContext:
        """Server-side TLS context (SSLConfiguration.scala:50-61). Modern
        defaults (TLS 1.2+) replace the reference's 2015-era cipher list."""
        if not self.enabled:
            raise ValueError("ssl.certfile is not configured in server.json")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_cert_chain(
            certfile=self.config.ssl_certfile,
            keyfile=self.config.ssl_keyfile,
            password=self.config.ssl_password,
        )
        return ctx

    def wrap_server(self, httpd, handshake_timeout: float = 10.0) -> None:
        """Wrap an ``http.server`` instance's listening socket in TLS.

        The handshake is deferred off the accept loop
        (``do_handshake_on_connect=False``) and performed — with a
        timeout — where the connection is handled (the worker thread
        under ThreadingMixIn). Otherwise a single client that connects
        and sends nothing would pin ``accept()`` inside the handshake
        and block every other connection."""
        httpd.socket = self.ssl_context().wrap_socket(
            httpd.socket, server_side=True, do_handshake_on_connect=False)
        orig_finish = httpd.finish_request

        def finish_request(request, client_address):
            request.settimeout(handshake_timeout)
            try:
                request.do_handshake()
            except (OSError, ssl.SSLError):
                httpd.shutdown_request(request)
                return
            request.settimeout(None)
            orig_finish(request, client_address)

        httpd.finish_request = finish_request
