"""TLS context construction for the HTTP daemons.

Parity: ``SSLConfiguration.scala:28-72`` — the reference loads a JKS
keystore named in ``server.conf`` and builds a TLS context for spray's
HTTPS binding. Here the PEM cert/key files named in ``server.json``
build an ``ssl.SSLContext``; any server's listening socket can be wrapped
with it (``wrap_server``).
"""

from __future__ import annotations

import ssl
from typing import Optional

from predictionio_tpu.common.auth import ServerConfig


class SSLConfiguration:
    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()

    @property
    def enabled(self) -> bool:
        return bool(self.config.ssl_certfile)

    def ssl_context(self) -> ssl.SSLContext:
        """Server-side TLS context (SSLConfiguration.scala:50-61). Modern
        defaults (TLS 1.2+) replace the reference's 2015-era cipher list."""
        if not self.enabled:
            raise ValueError("ssl.certfile is not configured in server.json")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        ctx.load_cert_chain(
            certfile=self.config.ssl_certfile,
            keyfile=self.config.ssl_keyfile,
            password=self.config.ssl_password,
        )
        return ctx

    def wrap_server(self, httpd) -> None:
        """Wrap an ``http.server`` instance's listening socket in TLS."""
        httpd.socket = self.ssl_context().wrap_socket(
            httpd.socket, server_side=True)
