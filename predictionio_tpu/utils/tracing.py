"""Tracing and profiling utilities.

The reference has no tracing beyond the query server's request counters
and Spark's own UI (SURVEY §5); the TPU build upgrades this to real
observability:

- :class:`LatencyHistogram` — thread-safe log-bucketed latency histogram
  with percentile estimates, used by the query server for per-query
  serving times (replacing the reference's single running average,
  ``CreateServer.scala:438-440,623-630``) and as the sample store behind
  every :class:`~predictionio_tpu.utils.metrics.Histogram` in the
  process-wide metrics registry.
- request-scoped tracing: :func:`ensure_request_id` accepts or mints an
  ``X-Request-ID``, carried through a :mod:`contextvars` var so
  :func:`span` log lines and storage-op records can attribute work to
  the request that caused it, across the thread handling it.
- **structured span trees**: :func:`span` is a real tracing span when a
  trace is active — trace_id / span_id / parent_id, start/end,
  attributes, error flag — recorded into a bounded thread-safe
  in-process :class:`TraceBuffer` with head sampling plus an always-keep
  lane for slow or errored traces (the slow-query log). A local trace
  root is opened with :func:`trace_scope` (the HTTP servers open one per
  request; ``pio train`` / ``pio batchpredict`` open one per run).
- **cross-process propagation**: W3C ``traceparent``
  (:func:`parse_traceparent` / :func:`current_traceparent`) carries the
  context over the resthttp storage wire and the feedback loop, so one
  trace covers query server → storage wire → event server. Each process
  retains ITS spans of the trace; ``GET /traces/<id>`` on each server
  returns the local fragment (same trace_id).
- **export**: :func:`trace_to_chrome` renders a retained trace as
  Chrome-trace-event JSON (loadable in Perfetto / ``chrome://tracing``);
  :func:`set_trace_dir` additionally appends every retained trace as a
  JSONL line (``traces-<pid>.jsonl``) and slow/errored summaries to
  ``slow-queries.log`` under the directory (``--trace-dir`` /
  ``$PIO_TRACE_DIR``). :func:`render_trace_html` is the dashboard's
  timeline view.
- kill switch: ``PIO_TRACING=0|off`` (or ``--tracing off``) disables
  span collection entirely — :func:`span` falls back to the log-line
  timer, so serving overhead stays negligible (the tracing analog of
  ``PIO_METRICS``).
- :func:`profile_trace` — wraps a block in a ``jax.profiler`` trace
  (viewable in TensorBoard/Perfetto) when a directory is given; the
  Spark-UI analog for XLA programs.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import logging
import os
import random
import re
import secrets
import threading
import time
from bisect import bisect_left
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

logger = logging.getLogger("pio.tracing")
slow_logger = logging.getLogger("pio.tracing.slow")

# bucket upper bounds in seconds (log-ish scale), last bucket = +inf
_BOUNDS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5,
           1.0, 2.0, 5.0)


class LatencyHistogram:
    """Thread-safe histogram with percentile estimation.

    Percentiles are estimated by linear interpolation inside the matched
    bucket — good to within a bucket width, which is what a serving
    dashboard needs. Default bounds are latency-shaped (seconds, log
    scale); pass ``bounds`` to count other magnitudes (batch sizes,
    queue depths).
    """

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        self._bounds: Tuple[float, ...] = (
            _BOUNDS if bounds is None else tuple(float(b) for b in bounds))
        if any(b2 <= b1 for b1, b2 in zip(self._bounds, self._bounds[1:])):
            raise ValueError("histogram bounds must be strictly increasing")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self._bounds) + 1)
        self._total = 0
        self._sum = 0.0
        self._max = 0.0
        self._last = 0.0
        self._exemplar: Optional[Tuple[str, float]] = None

    @property
    def bounds(self) -> Tuple[float, ...]:
        return self._bounds

    def record(self, seconds: float,
               exemplar: Optional[str] = None) -> None:
        # bisect_left over the precomputed bounds: first bound >= value,
        # i.e. the same ``le`` bucket the old linear scan picked —
        # O(log n) instead of O(n) per observation on the hot path
        i = bisect_left(self._bounds, seconds)
        with self._lock:
            self._counts[i] += 1
            self._total += 1
            self._sum += seconds
            self._last = seconds
            if seconds > self._max:
                self._max = seconds
            if exemplar is not None:
                # trace-id exemplar: the most recent traced observation,
                # so a regressed histogram links to an openable trace
                self._exemplar = (exemplar, seconds)

    @property
    def exemplar(self) -> Optional[Tuple[str, float]]:
        """(trace_id, value) of the most recent traced observation."""
        with self._lock:
            return self._exemplar

    def _percentile_locked(self, q: float) -> float:
        if self._total == 0:
            return 0.0
        target = q * self._total
        acc = 0
        for i, c in enumerate(self._counts):
            if acc + c >= target and c > 0:
                lo = 0.0 if i == 0 else self._bounds[i - 1]
                hi = self._bounds[i] if i < len(self._bounds) else self._max
                frac = (target - acc) / c
                return lo + (max(hi, lo) - lo) * frac
            acc += c
        return self._max

    def summary(self) -> Dict[str, object]:
        with self._lock:
            if self._total == 0:
                return {"count": 0, "sumSec": 0.0}
            return {
                "count": self._total,
                "sumSec": self._sum,
                "meanSec": self._sum / self._total,
                "lastSec": self._last,
                "maxSec": self._max,
                "p50Sec": self._percentile_locked(0.50),
                "p90Sec": self._percentile_locked(0.90),
                "p99Sec": self._percentile_locked(0.99),
            }

    def buckets(self) -> List[Dict[str, object]]:
        """Per-bucket counts (NOT cumulative; see :meth:`cumulative` for
        the Prometheus ``le`` view)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        for i, c in enumerate(counts):
            le = self._bounds[i] if i < len(self._bounds) else float("inf")
            out.append({"le": le, "count": c})
        return out

    @staticmethod
    def cumulate(counts: Sequence[int]) -> List[int]:
        """Per-bucket counts -> cumulative ``le`` counts. THE accumulation
        rule of the Prometheus histogram contract — both registry
        renderers and :meth:`cumulative` route through it so the
        exposition can never drift from this method."""
        out = []
        acc = 0
        for c in counts:
            acc += c
            out.append(acc)
        return out

    def cumulative(self) -> List[Dict[str, object]]:
        """Cumulative ``le`` buckets — the Prometheus histogram contract:
        each bucket counts every observation ≤ its bound, and the +inf
        bucket equals the total count (scrape-correct exposition)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        for i, acc in enumerate(self.cumulate(counts)):
            le = self._bounds[i] if i < len(self._bounds) else float("inf")
            out.append({"le": le, "count": acc})
        return out

    def snapshot(self) -> Tuple[List[int], int, float, float, float]:
        """Consistent (counts, total, sum, max, last) under one lock."""
        with self._lock:
            return (list(self._counts), self._total, self._sum, self._max,
                    self._last)

    @classmethod
    def from_state(cls, bounds: Sequence[float], counts: Sequence[int],
                   total: Optional[int] = None, sum_sec: float = 0.0,
                   max_sec: float = 0.0,
                   last_sec: float = 0.0) -> "LatencyHistogram":
        """Rebuild a histogram from an externalized state (a parsed
        remote ``/metrics`` exposition, a snapshot entry) so fleet
        federation can fold member series through :meth:`merge` with
        exactly the local aggregation rules. ``bounds`` are the finite
        bucket bounds (the implicit +inf bucket is ``counts[-1]``);
        ``counts`` are per-bucket (NOT cumulative)."""
        h = cls(bounds=tuple(float(b) for b in bounds))
        counts = [int(c) for c in counts]
        if len(counts) != len(h._bounds) + 1:
            raise ValueError(
                "histogram state needs %d counts for %d bounds, got %d"
                % (len(h._bounds) + 1, len(h._bounds), len(counts)))
        if any(c < 0 for c in counts):
            raise ValueError("histogram bucket counts must be >= 0")
        h._counts = counts
        h._total = int(total) if total is not None else sum(counts)
        h._sum = float(sum_sec)
        h._max = float(max_sec)
        h._last = float(last_sec)
        return h

    def merge(self, other: "LatencyHistogram") -> None:
        """Fold ``other``'s observations into this histogram (registry
        snapshot aggregation). Bounds must match; ``other`` is read under
        its own lock first so the merge never holds both locks at once."""
        if other._bounds != self._bounds:
            raise ValueError("cannot merge histograms with different bounds")
        counts, total, sum_, max_, last = other.snapshot()
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._total += total
            self._sum += sum_
            if max_ > self._max:
                self._max = max_
            if total:
                self._last = last

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._total = 0
            self._sum = 0.0
            self._max = 0.0
            self._last = 0.0
            self._exemplar = None


# ---------------------------------------------------------------------------
# Request-scoped tracing
# ---------------------------------------------------------------------------

# The id of the HTTP request (or CLI run) the current thread is working
# for. contextvars propagate per-thread here: each server handler thread
# sets it on entry, so storage-op records and span() lines deep in the
# stack attribute themselves without any parameter threading.
_request_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "pio_request_id", default=None)

# wire-safe id: printable, header-friendly, bounded
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._\-]{1,128}$")


def current_request_id() -> Optional[str]:
    return _request_id.get()


def set_request_id(rid: Optional[str]) -> contextvars.Token:
    """Bind the current context to ``rid``; returns the token for
    :func:`reset_request_id`."""
    return _request_id.set(rid)


def reset_request_id(token: contextvars.Token) -> None:
    _request_id.reset(token)


def ensure_request_id(given: Optional[str] = None) -> str:
    """Accept a client-supplied ``X-Request-ID`` when it is wire-safe,
    else mint a fresh one (16 hex chars)."""
    if given and _REQUEST_ID_RE.match(given):
        return given
    return secrets.token_hex(8)


@contextlib.contextmanager
def request_scope(given: Optional[str] = None):
    """Context manager binding a request id for the block; yields the id."""
    rid = ensure_request_id(given)
    token = set_request_id(rid)
    try:
        yield rid
    finally:
        reset_request_id(token)


# ---------------------------------------------------------------------------
# Structured spans — trace context + W3C traceparent
# ---------------------------------------------------------------------------

# monotonic→epoch anchor: every span timestamp is this one wall-clock
# reading plus a perf_counter delta, so all spans of a process share one
# clock — a child's start can never precede its parent's and integer-µs
# Chrome export stays monotonically consistent
_EPOCH_ANCHOR = time.time() - time.perf_counter()


def _now() -> float:
    return _EPOCH_ANCHOR + time.perf_counter()


# ids need uniqueness, not cryptographic strength — token_hex pays an
# os.urandom syscall per id, which dominated the per-span cost. One
# secrets-seeded PRNG per thread keeps ids unpredictable-enough and ~4x
# cheaper on the serving hot path.
_id_rng = threading.local()

_PID = os.getpid()
if hasattr(os, "register_at_fork"):  # keep span pids honest across fork
    os.register_at_fork(
        after_in_child=lambda: globals().__setitem__("_PID", os.getpid()))


def _rng() -> random.Random:
    rng = getattr(_id_rng, "rng", None)
    if rng is None:
        rng = _id_rng.rng = random.Random(secrets.randbits(64))
    return rng


def new_trace_id() -> str:
    return f"{_rng().getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_rng().getrandbits(64):016x}"


class SpanContext:
    """(trace_id, active span_id, sampled) — what propagates: into child
    spans in-process, as ``traceparent`` across processes."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def __repr__(self) -> str:
        return (f"SpanContext({self.trace_id!r}, {self.span_id!r}, "
                f"sampled={self.sampled})")


_trace_ctx: contextvars.ContextVar[Optional[SpanContext]] = \
    contextvars.ContextVar("pio_trace_ctx", default=None)


def current_trace_context() -> Optional[SpanContext]:
    return _trace_ctx.get()


def current_trace_id() -> Optional[str]:
    ctx = _trace_ctx.get()
    return ctx.trace_id if ctx is not None else None


# W3C Trace Context, version 00: 2-2-32-16-2 hex fields
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """A remote parent from a ``traceparent`` header, or None for any
    absent/malformed/all-zero value (a bad header must never break a
    request — the server just starts a fresh trace)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id,
                       sampled=bool(int(flags, 16) & 0x01))


def format_traceparent(ctx: SpanContext) -> str:
    return (f"00-{ctx.trace_id}-{ctx.span_id}-"
            f"{'01' if ctx.sampled else '00'}")


def current_traceparent() -> Optional[str]:
    """The header value to inject into an outgoing request (resthttp
    wire, feedback POST), or None when no trace is active."""
    ctx = _trace_ctx.get()
    return format_traceparent(ctx) if ctx is not None else None


def current_sampled_trace_id() -> Optional[str]:
    """The active trace id ONLY when head sampling retained it — what a
    histogram exemplar may point at (an unsampled trace's id would 404
    on GET /traces/<id> unless it later turns out slow/errored)."""
    ctx = _trace_ctx.get()
    return ctx.trace_id if ctx is not None and ctx.sampled else None


def outbound_context_headers() -> Dict[str, str]:
    """THE outbound propagation rule: the headers every cross-process
    call (resthttp wire, feedback POST) forwards so the receiving
    process joins this request's attribution — one definition, used by
    every client site."""
    headers: Dict[str, str] = {}
    rid = _request_id.get()
    if rid:
        headers["X-Request-ID"] = rid
    ctx = _trace_ctx.get()
    if ctx is not None:
        headers["traceparent"] = format_traceparent(ctx)
    return headers


def carrying_context(fn: Callable) -> Callable:
    """Wrap ``fn`` to run under a snapshot of the CURRENT contextvars
    (request id + trace context): hand the result to a worker thread and
    the work stays attributed to this request/trace."""
    snapshot = contextvars.copy_context()
    return lambda *args, **kwargs: snapshot.run(fn, *args, **kwargs)


class Span:
    """One timed operation in a trace tree."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "end", "attributes", "error", "thread")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str,
                 attributes: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = _now()
        self.end: Optional[float] = None
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.error = False
        self.thread = threading.get_ident()

    def duration(self) -> float:
        return (self.end if self.end is not None else _now()) - self.start

    def to_dict(self) -> Dict[str, Any]:
        return {
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end if self.end is not None else self.start,
            "durationSec": round(self.duration(), 9),
            "attributes": self.attributes,
            "error": self.error,
            "thread": self.thread,
            "pid": _PID,
        }


def _iso(epoch: float) -> str:
    import datetime as _dt

    return _dt.datetime.fromtimestamp(
        epoch, tz=_dt.timezone.utc).isoformat()


class TraceBuffer:
    """Bounded thread-safe store of finished traces.

    - spans of in-flight traces accumulate per trace_id (capped at
      ``max_spans_per_trace``; overflow is counted, not stored);
    - when a LOCAL ROOT span ends (:meth:`flush`), the trace is retained
      iff it was head-sampled OR slow (duration ≥
      ``slow_threshold_sec``) OR errored — the always-keep lane;
    - retained traces live in a FIFO ring of ``max_traces`` (oldest
      evicted first); slow/errored roots additionally append a summary
      to the slow-query log ring (and the ``pio.tracing.slow`` logger);
    - the head-sampling decision is a seeded :class:`random.Random`, so
      a fixed seed reproduces the exact keep/drop sequence.
    """

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 512,
                 max_slow: int = 256,
                 sample_rate: Optional[float] = None,
                 slow_threshold_sec: Optional[float] = None,
                 enabled: Optional[bool] = None,
                 seed: Optional[int] = None):
        def env_float(name: str, default: float) -> float:
            # a malformed env knob must not crash every pio command at
            # import (the module singleton evaluates this) — same
            # tolerance contract as parse_traceparent
            raw = os.environ.get(name)
            if raw is None:
                return default
            try:
                return float(raw)
            except ValueError:
                logger.warning("ignoring malformed %s=%r (using %s)",
                               name, raw, default)
                return default

        if sample_rate is None:
            sample_rate = env_float("PIO_TRACE_SAMPLE", 1.0)
        if slow_threshold_sec is None:
            slow_threshold_sec = env_float("PIO_TRACE_SLOW_SEC", 0.5)
        if enabled is None:
            enabled = os.environ.get("PIO_TRACING", "1").strip().lower() \
                not in ("0", "off", "false")
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self.slow_threshold_sec = float(slow_threshold_sec)
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        # open local roots per trace_id (a trace can have several, e.g.
        # two resthttp calls of one remote query hitting this server)
        self._roots: Dict[str, int] = {}
        self._open: Dict[str, List[Span]] = {}
        self._dropped: Dict[str, int] = {}
        self._done: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        self._slow: "collections.deque" = collections.deque(maxlen=max_slow)
        self._export_dir: Optional[str] = None
        self._export_lock = threading.Lock()

    # -- sampling ----------------------------------------------------------
    def sample(self) -> bool:
        """One head-sampling decision (deterministic under a seed)."""
        rate = self.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < rate

    # -- collection --------------------------------------------------------
    def root_started(self, trace_id: str) -> None:
        with self._lock:
            self._roots[trace_id] = self._roots.get(trace_id, 0) + 1

    def add_span(self, span: Span) -> None:
        """A finished span. Goes to the in-flight set while a local root
        is open; a late span (e.g. async work outliving its request)
        lands directly on the retained record, or is dropped when the
        trace was not retained."""
        if not self.enabled:
            return
        tid = span.trace_id
        with self._lock:
            if self._roots.get(tid):
                spans = self._open.setdefault(tid, [])
                if len(spans) >= self.max_spans_per_trace:
                    self._dropped[tid] = self._dropped.get(tid, 0) + 1
                    return
                spans.append(span)
                return
            rec = self._done.get(tid)
            if rec is not None \
                    and len(rec["spans"]) < self.max_spans_per_trace:
                rec["spans"].append(span)

    def flush(self, root: Span, sampled: bool) -> None:
        """Retire a local root: decide retention, update the slow-query
        log, export. Called by :func:`trace_scope` at root exit. Span
        objects are retained as-is — rendering them to dicts happens at
        READ time (``get``/``index``/export), off the serving path."""
        if not self.enabled:
            return
        tid = root.trace_id
        duration = root.duration()
        # batch jobs (train, batchpredict) exempt themselves: a 40min
        # train pass is not a slow QUERY and must not drown the log
        slow = duration >= self.slow_threshold_sec \
            and not root.attributes.get("slowExempt")
        err = root.error
        record: Optional[Dict[str, Any]] = None
        new_spans: List[Span] = []
        slow_entry: Optional[Dict[str, Any]] = None
        with self._lock:
            open_roots = self._roots.get(tid, 1) - 1
            if open_roots > 0:
                self._roots[tid] = open_roots
            else:
                self._roots.pop(tid, None)
            if open_roots > 0 and not (sampled or slow or err):
                # a sibling root is still collecting; leave the spans
                self._open.setdefault(tid, []).append(root)
                return
            new_spans = self._open.pop(tid, [])
            new_spans.append(root)
            dropped = self._dropped.pop(tid, 0)
            keep = sampled or slow or err
            existing = self._done.get(tid)
            if existing is not None:
                existing["spans"].extend(new_spans)
                existing["droppedSpans"] += dropped
                existing["error"] = existing["error"] or err
                existing["slow"] = existing["slow"] or slow
                existing["durationSec"] = max(existing["durationSec"],
                                              round(duration, 9))
                self._done.move_to_end(tid)
                record = existing
            elif keep:
                record = {
                    "traceId": tid,
                    "root": root.name,
                    "startEpoch": root.start,
                    "durationSec": round(duration, 9),
                    "slow": slow,
                    "error": err,
                    "sampled": sampled,
                    "droppedSpans": dropped,
                    "process": {"pid": _PID},
                    "spans": list(new_spans),
                }
                self._done[tid] = record
                while len(self._done) > self.max_traces:
                    self._done.popitem(last=False)
            if slow or err:
                slow_entry = {
                    "time": _iso(root.start),
                    "traceId": tid,
                    "name": root.name,
                    "durationSec": round(duration, 6),
                    "error": err,
                    "spans": len(new_spans),
                }
                # dispatch context (PR 12): the device-telemetry layer
                # attaches its flight record to the device.* span, so a
                # slow exemplar names its bucket/batch/fill/kernel/AOT
                # outcome — diagnosable without reproducing it
                disp = None
                for s in new_spans:
                    d = getattr(s, "attributes", {}).get("dispatch")
                    if d is not None:
                        disp = d
                if disp is not None:
                    slow_entry["dispatch"] = disp
                capture = PROFILER.active_dir
                if capture:
                    # a profiler capture was running while this query
                    # was slow: the slow log links straight to it
                    slow_entry["profileCapture"] = capture
                self._slow.append(slow_entry)
        if slow_entry is not None:
            slow_logger.warning(
                "%s trace %s: %s took %.3fs (%d spans)",
                "errored" if err else "slow", tid, root.name, duration,
                slow_entry["spans"])
        if record is not None and self._export_dir:
            self._export(self._render(record, spans=new_spans),
                         slow_entry)

    @staticmethod
    def _render(record: Dict[str, Any],
                spans: Optional[List[Any]] = None) -> Dict[str, Any]:
        """A retained record as pure JSON-shaped data (spans may still
        be live Span objects internally)."""
        use = record["spans"] if spans is None else spans
        out = {k: v for k, v in record.items()
               if k not in ("spans", "startEpoch")}
        out["startTime"] = _iso(record["startEpoch"])
        out["spans"] = [s.to_dict() if isinstance(s, Span) else s
                        for s in use]
        return out

    # -- reads -------------------------------------------------------------
    def index(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Summaries of retained traces, newest first."""
        with self._lock:
            recent = [(rec, len(rec["spans"]))
                      for rec in list(self._done.values())[-limit:]]
        out = []
        for rec, n_spans in reversed(recent):
            summary = {k: rec[k] for k in
                       ("traceId", "root", "durationSec", "slow",
                        "error", "droppedSpans")}
            summary["startTime"] = _iso(rec["startEpoch"])
            summary["spans"] = n_spans
            out.append(summary)
        return out

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._done.get(trace_id)
            if rec is None:
                return None
            spans = list(rec["spans"])
        return self._render({**rec, "spans": spans})

    def slow_log(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Recent slow/errored trace summaries, newest first."""
        with self._lock:
            return list(self._slow)[-limit:][::-1]

    def reset(self) -> None:
        with self._lock:
            self._roots.clear()
            self._open.clear()
            self._dropped.clear()
            self._done.clear()
            self._slow.clear()

    # -- file export -------------------------------------------------------
    def set_export_dir(self, path: Optional[str]) -> None:
        if path:
            os.makedirs(path, exist_ok=True)
        self._export_dir = path

    def _export(self, record: Dict[str, Any],
                slow_entry: Optional[Dict[str, Any]]) -> None:
        d = self._export_dir
        if not d:
            return
        try:
            with self._export_lock:
                path = os.path.join(d, f"traces-{os.getpid()}.jsonl")
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(record, separators=(",", ":"))
                            + "\n")
                if slow_entry is not None:
                    with open(os.path.join(d, "slow-queries.log"), "a",
                              encoding="utf-8") as f:
                        f.write(json.dumps(slow_entry,
                                           separators=(",", ":")) + "\n")
        except OSError:
            logger.exception("trace export to %s failed", d)


# the process-wide buffer (the analog of metrics.REGISTRY)
TRACES = TraceBuffer()


def trace_buffer() -> TraceBuffer:
    return TRACES


def set_tracing_enabled(enabled: bool) -> None:
    """Process-wide tracing switch (``--tracing on|off`` /
    ``PIO_TRACING``). Disabled, :func:`span` is the plain log-line timer
    and :func:`trace_scope` yields None."""
    TRACES.enabled = bool(enabled)


def set_trace_dir(path: Optional[str]) -> None:
    """JSONL-export every retained trace (and slow-query summaries) to
    files under ``path`` (``--trace-dir`` / ``$PIO_TRACE_DIR``)."""
    TRACES.set_export_dir(path)


def load_traces_from_dir(path: str, trace_id: Optional[str] = None,
                         limit: Optional[int] = None
                         ) -> List[Dict[str, Any]]:
    """Read trace records back from a ``--trace-dir``, merging fragments
    of the same trace_id across files (i.e. across processes)."""
    # the fold itself (topmost-fragment-wins naming, max-duration,
    # OR'd error/slow) is shared with the balancer's live trace
    # assembly — see predictionio_tpu/obs/assemble.py. Lazy import:
    # obs is a subpackage consumer of this module.
    from predictionio_tpu.obs import assemble as _assemble
    merged: "collections.OrderedDict[str, Dict[str, Any]]" = \
        collections.OrderedDict()
    try:
        names = sorted(n for n in os.listdir(path)
                       if n.startswith("traces-") and n.endswith(".jsonl"))
    except OSError:
        return []
    for name in names:
        try:
            with open(os.path.join(path, name), "r",
                      encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if trace_id is not None and trace_id not in line:
                        # substring pre-filter: a single-trace lookup
                        # over a months-old export must skip ~every
                        # line at I/O speed, not json-parse it
                        continue
                    try:
                        rec = json.loads(line)
                        tid = rec["traceId"]
                    except (json.JSONDecodeError, TypeError, KeyError):
                        continue
                    if trace_id is not None and tid != trace_id:
                        continue  # exact check behind the substring gate
                    prior = merged.get(tid)
                    if prior is None:
                        merged[tid] = rec
                    else:
                        # the fragment holding the TOPMOST span (no
                        # parent) names the merged trace: "pio.train",
                        # not the event server's wire-request root
                        merged[tid] = _assemble.fold_fragment(prior, rec)
        except OSError:
            continue
    out = list(merged.values())
    if limit is not None:
        out = out[-limit:]
    return out


def load_slow_log_from_dir(path: str, limit: int = 50
                           ) -> List[Dict[str, Any]]:
    """The last ``limit`` slow-query-log entries under a trace dir."""
    entries: List[Dict[str, Any]] = []
    try:
        with open(os.path.join(path, "slow-queries.log"), "r",
                  encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return []
    for line in lines[-limit:]:
        line = line.strip()
        if not line:
            continue
        try:
            entries.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return entries[::-1]


# -- span machinery ---------------------------------------------------------

def begin_span(name: str, attributes: Optional[Dict[str, Any]] = None,
               set_current: bool = True
               ) -> Tuple[Optional[Span], Optional[contextvars.Token]]:
    """Manual span start: a child of the current context, or (None,
    None) when no trace is active / tracing is off. ``set_current=False``
    skips rebinding the contextvar (for spans finished by callbacks that
    may not nest, e.g. a lazy storage scan)."""
    if not TRACES.enabled:
        return None, None
    ctx = _trace_ctx.get()
    if ctx is None:
        return None, None
    sp = Span(ctx.trace_id, new_span_id(), ctx.span_id, name, attributes)
    token = None
    if set_current:
        token = _trace_ctx.set(
            SpanContext(ctx.trace_id, sp.span_id, ctx.sampled))
    return sp, token


def finish_span(sp: Optional[Span],
                token: Optional[contextvars.Token] = None,
                error: Optional[BaseException] = None) -> None:
    """Manual span end: stamps the end time, flags the error, restores
    the context and records the span into the buffer."""
    if token is not None:
        _trace_ctx.reset(token)
    if sp is None:
        return
    sp.end = _now()
    if error is not None:
        sp.error = True
        sp.attributes.setdefault("exception", type(error).__name__)
    TRACES.add_span(sp)


@contextlib.contextmanager
def trace_scope(name: str, parent: Optional[SpanContext] = None,
                attributes: Optional[Dict[str, Any]] = None,
                slow_exempt: bool = False):
    """Open a LOCAL TRACE ROOT for the block and flush it at exit.

    - no active context, no ``parent``: a fresh trace (head-sampled);
    - ``parent`` given (a remote W3C traceparent): this process's root
      joins that trace and inherits its sampling decision;
    - a local context already active: degrades to a plain child
      :func:`span` (nested scopes don't start new traces).

    ``slow_exempt`` keeps a long-by-design job (train, batchpredict)
    out of the slow-QUERY log. Yields the root :class:`Span` (mutable:
    handlers set status attributes / the error flag before exit), or
    None when tracing is disabled."""
    buf = TRACES
    if not buf.enabled:
        yield None
        return
    if _trace_ctx.get() is not None:
        with span(name, attributes=attributes) as sp:
            yield sp
        return
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
        sampled = parent.sampled
    else:
        trace_id, parent_id = new_trace_id(), None
        sampled = buf.sample()
    attributes = dict(attributes or {})
    if slow_exempt:
        attributes["slowExempt"] = True
    root = Span(trace_id, new_span_id(), parent_id, name, attributes)
    buf.root_started(trace_id)
    token = _trace_ctx.set(SpanContext(trace_id, root.span_id, sampled))
    error: Optional[BaseException] = None
    try:
        yield root
    except BaseException as e:
        error = e
        raise
    finally:
        _trace_ctx.reset(token)
        root.end = _now()
        if error is not None:
            root.error = True
            root.attributes.setdefault("exception", type(error).__name__)
        buf.flush(root, sampled)  # flush records the root itself


@contextlib.contextmanager
def span(name: str, level: int = logging.DEBUG,
         histogram: Optional[LatencyHistogram] = None,
         attributes: Optional[Dict[str, Any]] = None):
    """Time a block. Inside an active trace this records a real child
    span (trace/span/parent ids, attributes, error flag) into the trace
    buffer; otherwise — or with tracing killed — it is exactly the old
    request-id-tagged log line. ``histogram`` additionally records the
    duration (how the DASE-stage spans feed ``pio_train_stage_seconds``).
    Yields the :class:`Span` (or None)."""
    t0 = time.perf_counter()
    sp, token = begin_span(name, attributes)
    error: Optional[BaseException] = None
    try:
        yield sp
    except BaseException as e:
        error = e
        raise
    finally:
        took = time.perf_counter() - t0
        finish_span(sp, token, error=error)
        if histogram is not None:
            histogram.record(took)
        rid = current_request_id()
        if rid:
            logger.log(level, "%s took %.3fs [rid=%s]", name, took, rid)
        else:
            logger.log(level, "%s took %.3fs", name, took)


def span_now() -> float:
    """The span clock (monotonic-anchored epoch seconds) — public so
    instrumentation that times work OUTSIDE the span machinery (the
    device-dispatch telemetry window) can stamp spans on the same clock
    every other span uses."""
    return _now()


def record_completed_span(name: str, start: float, end: float,
                          attributes: Optional[Dict[str, Any]] = None,
                          parent: Optional[SpanContext] = None
                          ) -> Optional[Span]:
    """Record an ALREADY-FINISHED span — for work whose window was
    timed with raw clock reads rather than a context manager (e.g. the
    dispatch→``block_until_ready`` device window, which must cost two
    monotonic reads, not a contextvar rebind). Parents under ``parent``
    when given, else the ambient context; no-ops (returns None) when
    tracing is off or no trace is active. ``start``/``end`` must come
    from :func:`span_now`."""
    if not TRACES.enabled:
        return None
    ctx = parent if parent is not None else _trace_ctx.get()
    if ctx is None:
        return None
    sp = Span(ctx.trace_id, new_span_id(), ctx.span_id, name, attributes)
    sp.start = float(start)
    sp.end = float(end)
    TRACES.add_span(sp)
    return sp


@contextlib.contextmanager
def detached_span(name: str, parent: Optional[SpanContext] = None,
                  attributes: Optional[Dict[str, Any]] = None):
    """A child span parented EXPLICITLY under ``parent`` (a SpanContext
    snapshot) instead of the ambient contextvar — for pipeline stages
    that run on worker threads the context never crossed (e.g. the
    ingest decode producer). Records into the trace buffer like any
    span, so Perfetto renders the cross-thread overlap; no-ops when
    tracing is off or no parent is supplied."""
    if not TRACES.enabled or parent is None:
        yield None
        return
    sp = Span(parent.trace_id, new_span_id(), parent.span_id, name,
              attributes)
    error: Optional[BaseException] = None
    try:
        yield sp
    except BaseException as e:
        error = e
        raise
    finally:
        sp.end = _now()
        if error is not None:
            sp.error = True
            sp.attributes.setdefault("exception", type(error).__name__)
        TRACES.add_span(sp)


class StageTimeline:
    """Thread-safe wall-span collector for pipeline overlap accounting.

    Each :meth:`scope` (or :meth:`wrap_iter` step) appends one
    ``(stage, start, end, thread)`` record in epoch seconds, from
    WHICHEVER thread ran it — producer decode spans interleave with
    consumer index/bucket spans. :meth:`summary` reduces them to
    per-stage busy totals, the union wall span, and the overlap ratio
    (busy/wall; 1.0 = fully serial, higher = real overlap);
    :meth:`to_json` is the bench's per-stage timeline artifact, and the
    same scopes mirror into the trace buffer (via :func:`detached_span`
    when a parent context is given) so Perfetto shows the identical
    picture."""

    def __init__(self):
        self._spans: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def add(self, stage: str, start: float, end: float) -> None:
        with self._lock:
            self._spans.append({
                "stage": stage, "start": start, "end": end,
                "durationSec": round(end - start, 6),
                "thread": threading.get_ident(),
            })

    @contextlib.contextmanager
    def scope(self, stage: str,
              trace_parent: Optional[SpanContext] = None):
        # _now(): monotonic-derived epoch (same clock as every Span) —
        # a wall-clock step mid-ingest must not corrupt durations
        with detached_span(f"ingest.{stage}", trace_parent):
            t0 = _now()
            try:
                yield
            finally:
                self.add(stage, t0, _now())

    def wrap_iter(self, it, stage: str,
                  trace_parent: Optional[SpanContext] = None):
        """Yield from ``it`` timing each ``next()`` as one stage span —
        run inside a producer thread this measures exactly the decode
        wall time, on the decode thread."""
        it = iter(it)
        while True:
            with self.scope(stage, trace_parent):
                try:
                    item = next(it)
                except StopIteration:
                    return
            yield item

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def summary(self, spans: Optional[List[Dict[str, Any]]] = None
                ) -> Dict[str, Any]:
        if spans is None:
            spans = self.spans()
        if not spans:
            return {"stages": {}, "wall_sec": 0.0, "busy_sec": 0.0,
                    "overlap_ratio": None}
        stages: Dict[str, Dict[str, Any]] = {}
        for s in spans:
            st = stages.setdefault(s["stage"],
                                   {"busy_sec": 0.0, "spans": 0,
                                    "first_start": s["start"],
                                    "last_end": s["end"]})
            st["busy_sec"] += s["end"] - s["start"]
            st["spans"] += 1
            st["first_start"] = min(st["first_start"], s["start"])
            st["last_end"] = max(st["last_end"], s["end"])
        wall = (max(s["end"] for s in spans)
                - min(s["start"] for s in spans))
        busy = sum(s["end"] - s["start"] for s in spans)
        for st in stages.values():
            st["busy_sec"] = round(st["busy_sec"], 4)
            st["wall_span_sec"] = round(st.pop("last_end")
                                        - st.pop("first_start"), 4)
        return {
            "stages": stages,
            "wall_sec": round(wall, 4),
            "busy_sec": round(busy, 4),
            "overlap_ratio": round(busy / wall, 3) if wall > 0 else None,
        }

    def to_json(self) -> Dict[str, Any]:
        # ONE snapshot for origin, span list, and summary — a stage
        # still recording on another thread (e.g. the warm-up compile)
        # must not land between them and tear the artifact
        spans = self.spans()
        base = min((s["start"] for s in spans), default=0.0)
        return {
            "origin_epoch_sec": base,
            "spans": [{**s, "start": round(s["start"] - base, 6),
                       "end": round(s["end"] - base, 6)}
                      for s in spans],
            "summary": self.summary(spans),
        }


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def trace_to_chrome(record: Dict[str, Any]) -> Dict[str, Any]:
    """A retained trace record as Chrome-trace-event JSON: one complete
    (``ph: "X"``) event per span, µs timestamps/durations. Loadable in
    Perfetto (ui.perfetto.dev) and ``chrome://tracing``. Integer-µs
    endpoints are truncated from the same monotonic clock, so a child
    event always sits inside its parent's [ts, ts+dur] window."""
    default_pid = (record.get("process") or {}).get("pid", 0)
    events = []
    for s in record.get("spans", ()):
        ts = int(float(s["start"]) * 1e6)
        end = int(float(s["end"]) * 1e6)
        args = {k: v for k, v in (s.get("attributes") or {}).items()}
        args["spanId"] = s.get("spanId")
        if s.get("parentId"):
            args["parentId"] = s["parentId"]
        if s.get("error"):
            args["error"] = True
        events.append({
            "name": s["name"],
            "cat": "pio",
            "ph": "X",
            "ts": ts,
            "dur": max(0, end - ts),
            "pid": s.get("pid", default_pid),
            "tid": s.get("thread", 0),
            "args": args,
        })
    return {
        "displayTimeUnit": "ms",
        "otherData": {
            "traceId": record.get("traceId"),
            "root": record.get("root"),
            "source": "predictionio-tpu",
        },
        "traceEvents": events,
    }


def render_trace_html(record: Dict[str, Any]) -> str:
    """A minimal self-contained HTML timeline of one trace (the
    dashboard's trace view): one bar per span, offset/width proportional
    to start/duration, indented by tree depth."""
    import html as _html

    spans = sorted(record.get("spans", ()),
                   key=lambda s: float(s["start"]))
    if spans:
        t0 = min(float(s["start"]) for s in spans)
        t1 = max(float(s["end"]) for s in spans)
    else:
        t0, t1 = 0.0, 1.0
    total = max(t1 - t0, 1e-9)
    by_id = {s.get("spanId"): s for s in spans}

    def depth(s, _seen=None) -> int:
        d = 0
        seen = set()
        cur = s
        while cur is not None and cur.get("parentId") in by_id:
            if cur.get("spanId") in seen:
                break
            seen.add(cur.get("spanId"))
            cur = by_id[cur["parentId"]]
            d += 1
        return d

    rows = []
    for s in spans:
        left = (float(s["start"]) - t0) / total * 100.0
        width = max((float(s["end"]) - float(s["start"])) / total * 100.0,
                    0.15)
        ms = (float(s["end"]) - float(s["start"])) * 1000.0
        pad = depth(s) * 14
        color = "#c0392b" if s.get("error") else "#2e86c1"
        name = _html.escape(str(s["name"]))
        pid = s.get("pid", "")
        rows.append(
            f"<div class='row'><div class='label' "
            f"style='padding-left:{pad}px'>{name} "
            f"<span class='ms'>{ms:.2f}ms · pid {pid}</span></div>"
            f"<div class='track'><div class='bar' style='left:{left:.3f}%;"
            f"width:{width:.3f}%;background:{color}'></div></div></div>")
    tid = _html.escape(str(record.get("traceId", "")))
    head = _html.escape(str(record.get("root", "")))
    dur = float(record.get("durationSec", 0.0)) * 1000.0
    flags = []
    if record.get("slow"):
        flags.append("SLOW")
    if record.get("error"):
        flags.append("ERROR")
    flag_s = (" [" + ", ".join(flags) + "]") if flags else ""
    return f"""<!DOCTYPE html>
<html><head><title>Trace {tid}</title><style>
body {{ font-family: monospace; margin: 16px; }}
.row {{ display: flex; align-items: center; margin: 1px 0; }}
.label {{ width: 42%; white-space: nowrap; overflow: hidden;
          text-overflow: ellipsis; font-size: 12px; }}
.ms {{ color: #888; }}
.track {{ position: relative; flex: 1; height: 14px;
          background: #f2f3f4; }}
.bar {{ position: absolute; top: 2px; height: 10px; min-width: 1px; }}
</style></head><body>
<h2>Trace {tid}{flag_s}</h2>
<p>root: {head} · {dur:.2f}ms · {len(rows)} spans ·
started {_html.escape(str(record.get('startTime', '')))}</p>
{''.join(rows)}
</body></html>"""


# ---------------------------------------------------------------------------
# jax.profiler wrapper
# ---------------------------------------------------------------------------

class ProfilerBusyError(RuntimeError):
    """``POST /profile/start`` while a capture is already running (the
    server renders this 409): ``jax.profiler`` is process-global, so
    captures are strictly single-flight."""


class ProfilerNotRunningError(RuntimeError):
    """``POST /profile/stop`` with no active capture (409)."""


class ProfilerCapture:
    """Single-flight on-demand ``jax.profiler`` capture for a LIVE
    process — the start/stop twin of :func:`profile_trace` (same
    counter, same jit-compile listener side effect), driven by the
    query server's ``POST /profile/start`` / ``/profile/stop``.

    Captures land under a ``profiles/`` subdirectory next to the
    ``--trace-dir`` JSONL exports (or ``$PIO_PROFILE_DIR``, or a
    temp directory as the last resort), and the slow-query log
    cross-links entries recorded while a capture was running."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dir: Optional[str] = None
        self._t0: float = 0.0

    @property
    def active_dir(self) -> Optional[str]:
        return self._dir

    def resolve_base_dir(self) -> str:
        """Where captures go: next to the trace export, else
        $PIO_PROFILE_DIR, else a fresh temp dir."""
        export = TRACES._export_dir
        if export:
            return os.path.join(export, "profiles")
        env = os.environ.get("PIO_PROFILE_DIR")
        if env:
            return env
        import tempfile

        return tempfile.mkdtemp(prefix="pio-profile-")

    def start(self, base_dir: Optional[str] = None) -> str:
        from predictionio_tpu.utils import metrics

        with self._lock:
            if self._dir is not None:
                raise ProfilerBusyError(
                    f"a profiler capture is already running "
                    f"({self._dir}); stop it first")
            base = base_dir or self.resolve_base_dir()
            path = os.path.join(
                base, time.strftime("profile-%Y%m%dT%H%M%SZ", time.gmtime()))
            os.makedirs(path, exist_ok=True)
            metrics.install_jit_compile_listener()
            import jax

            jax.profiler.start_trace(path)
            self._dir = path
            self._t0 = time.perf_counter()
        metrics.PROFILE_CAPTURES_ACTIVE.set(1)
        logger.info("profiler capture started -> %s", path)
        return path

    def stop(self) -> Dict[str, Any]:
        from predictionio_tpu.utils import metrics

        with self._lock:
            if self._dir is None:
                raise ProfilerNotRunningError(
                    "no profiler capture is running")
            import jax

            try:
                jax.profiler.stop_trace()
            finally:
                # whatever stop_trace did, the capture is OVER: clear
                # the slot AND the gauge, or a failed stop would pin
                # pio_profile_capture_active at 1 with nothing running
                path, self._dir = self._dir, None
                metrics.PROFILE_CAPTURES_ACTIVE.set(0)
            took = time.perf_counter() - self._t0
        metrics.PROFILE_TRACES.inc()
        logger.info("profiler capture written to %s (%.3fs)", path, took)
        return {"profileDir": path, "durationSec": round(took, 3)}


PROFILER = ProfilerCapture()


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str] = None):
    """Capture a jax.profiler trace of the block into ``trace_dir``
    (no-op when None). View with TensorBoard's profile plugin or
    Perfetto. Each capture is counted in the metrics registry
    (``pio_profile_traces_total``) and, as a side effect of the first
    call, installs the JIT-compile listener so compile count/time show
    up alongside the trace."""
    if not trace_dir:
        yield
        return
    from predictionio_tpu.utils import metrics

    metrics.install_jit_compile_listener()
    import jax

    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        yield
    metrics.PROFILE_TRACES.inc()
    logger.info("profiler trace written to %s (%.3fs)", trace_dir,
                time.perf_counter() - t0)
