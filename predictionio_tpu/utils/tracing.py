"""Tracing and profiling utilities.

The reference has no tracing beyond the query server's request counters
and Spark's own UI (SURVEY §5); the TPU build upgrades this to real
observability:

- :class:`LatencyHistogram` — thread-safe log-bucketed latency histogram
  with percentile estimates, used by the query server for per-query
  serving times (replacing the reference's single running average,
  ``CreateServer.scala:438-440,623-630``).
- :func:`profile_trace` — wraps a block in a ``jax.profiler`` trace
  (viewable in TensorBoard/Perfetto) when a directory is given; the
  Spark-UI analog for XLA programs.
- :func:`span` — debug-log a named wall-clock span.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("pio.tracing")

# bucket upper bounds in seconds (log-ish scale), last bucket = +inf
_BOUNDS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5,
           1.0, 2.0, 5.0)


class LatencyHistogram:
    """Thread-safe latency histogram with percentile estimation.

    Percentiles are estimated by linear interpolation inside the matched
    bucket — good to within a bucket width, which is what a serving
    dashboard needs.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * (len(_BOUNDS) + 1)
        self._total = 0
        self._sum = 0.0
        self._max = 0.0
        self._last = 0.0

    def record(self, seconds: float) -> None:
        i = 0
        while i < len(_BOUNDS) and seconds > _BOUNDS[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._total += 1
            self._sum += seconds
            self._last = seconds
            if seconds > self._max:
                self._max = seconds

    def _percentile_locked(self, q: float) -> float:
        if self._total == 0:
            return 0.0
        target = q * self._total
        acc = 0
        for i, c in enumerate(self._counts):
            if acc + c >= target and c > 0:
                lo = 0.0 if i == 0 else _BOUNDS[i - 1]
                hi = _BOUNDS[i] if i < len(_BOUNDS) else self._max
                frac = (target - acc) / c
                return lo + (max(hi, lo) - lo) * frac
            acc += c
        return self._max

    def summary(self) -> Dict[str, object]:
        with self._lock:
            if self._total == 0:
                return {"count": 0}
            return {
                "count": self._total,
                "meanSec": self._sum / self._total,
                "lastSec": self._last,
                "maxSec": self._max,
                "p50Sec": self._percentile_locked(0.50),
                "p90Sec": self._percentile_locked(0.90),
                "p99Sec": self._percentile_locked(0.99),
            }

    def buckets(self) -> List[Dict[str, object]]:
        with self._lock:
            counts = list(self._counts)
        out = []
        for i, c in enumerate(counts):
            le = _BOUNDS[i] if i < len(_BOUNDS) else float("inf")
            out.append({"le": le, "count": c})
        return out


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str] = None):
    """Capture a jax.profiler trace of the block into ``trace_dir``
    (no-op when None). View with TensorBoard's profile plugin or
    Perfetto."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
    logger.info("profiler trace written to %s", trace_dir)


@contextlib.contextmanager
def span(name: str, level: int = logging.DEBUG):
    """Log the wall-clock duration of a block."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.log(level, "%s took %.3fs", name, time.perf_counter() - t0)
