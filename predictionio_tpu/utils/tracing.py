"""Tracing and profiling utilities.

The reference has no tracing beyond the query server's request counters
and Spark's own UI (SURVEY §5); the TPU build upgrades this to real
observability:

- :class:`LatencyHistogram` — thread-safe log-bucketed latency histogram
  with percentile estimates, used by the query server for per-query
  serving times (replacing the reference's single running average,
  ``CreateServer.scala:438-440,623-630``) and as the sample store behind
  every :class:`~predictionio_tpu.utils.metrics.Histogram` in the
  process-wide metrics registry.
- request-scoped tracing: :func:`ensure_request_id` accepts or mints an
  ``X-Request-ID``, carried through a :mod:`contextvars` var so
  :func:`span` log lines and storage-op records can attribute work to
  the request that caused it, across the thread handling it.
- :func:`profile_trace` — wraps a block in a ``jax.profiler`` trace
  (viewable in TensorBoard/Perfetto) when a directory is given; the
  Spark-UI analog for XLA programs.
- :func:`span` — debug-log a named wall-clock span (request-id tagged).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import re
import secrets
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("pio.tracing")

# bucket upper bounds in seconds (log-ish scale), last bucket = +inf
_BOUNDS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5,
           1.0, 2.0, 5.0)


class LatencyHistogram:
    """Thread-safe histogram with percentile estimation.

    Percentiles are estimated by linear interpolation inside the matched
    bucket — good to within a bucket width, which is what a serving
    dashboard needs. Default bounds are latency-shaped (seconds, log
    scale); pass ``bounds`` to count other magnitudes (batch sizes,
    queue depths).
    """

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        self._bounds: Tuple[float, ...] = (
            _BOUNDS if bounds is None else tuple(float(b) for b in bounds))
        if any(b2 <= b1 for b1, b2 in zip(self._bounds, self._bounds[1:])):
            raise ValueError("histogram bounds must be strictly increasing")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self._bounds) + 1)
        self._total = 0
        self._sum = 0.0
        self._max = 0.0
        self._last = 0.0

    @property
    def bounds(self) -> Tuple[float, ...]:
        return self._bounds

    def record(self, seconds: float) -> None:
        i = 0
        while i < len(self._bounds) and seconds > self._bounds[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._total += 1
            self._sum += seconds
            self._last = seconds
            if seconds > self._max:
                self._max = seconds

    def _percentile_locked(self, q: float) -> float:
        if self._total == 0:
            return 0.0
        target = q * self._total
        acc = 0
        for i, c in enumerate(self._counts):
            if acc + c >= target and c > 0:
                lo = 0.0 if i == 0 else self._bounds[i - 1]
                hi = self._bounds[i] if i < len(self._bounds) else self._max
                frac = (target - acc) / c
                return lo + (max(hi, lo) - lo) * frac
            acc += c
        return self._max

    def summary(self) -> Dict[str, object]:
        with self._lock:
            if self._total == 0:
                return {"count": 0, "sumSec": 0.0}
            return {
                "count": self._total,
                "sumSec": self._sum,
                "meanSec": self._sum / self._total,
                "lastSec": self._last,
                "maxSec": self._max,
                "p50Sec": self._percentile_locked(0.50),
                "p90Sec": self._percentile_locked(0.90),
                "p99Sec": self._percentile_locked(0.99),
            }

    def buckets(self) -> List[Dict[str, object]]:
        """Per-bucket counts (NOT cumulative; see :meth:`cumulative` for
        the Prometheus ``le`` view)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        for i, c in enumerate(counts):
            le = self._bounds[i] if i < len(self._bounds) else float("inf")
            out.append({"le": le, "count": c})
        return out

    @staticmethod
    def cumulate(counts: Sequence[int]) -> List[int]:
        """Per-bucket counts -> cumulative ``le`` counts. THE accumulation
        rule of the Prometheus histogram contract — both registry
        renderers and :meth:`cumulative` route through it so the
        exposition can never drift from this method."""
        out = []
        acc = 0
        for c in counts:
            acc += c
            out.append(acc)
        return out

    def cumulative(self) -> List[Dict[str, object]]:
        """Cumulative ``le`` buckets — the Prometheus histogram contract:
        each bucket counts every observation ≤ its bound, and the +inf
        bucket equals the total count (scrape-correct exposition)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        for i, acc in enumerate(self.cumulate(counts)):
            le = self._bounds[i] if i < len(self._bounds) else float("inf")
            out.append({"le": le, "count": acc})
        return out

    def snapshot(self) -> Tuple[List[int], int, float, float, float]:
        """Consistent (counts, total, sum, max, last) under one lock."""
        with self._lock:
            return (list(self._counts), self._total, self._sum, self._max,
                    self._last)

    def merge(self, other: "LatencyHistogram") -> None:
        """Fold ``other``'s observations into this histogram (registry
        snapshot aggregation). Bounds must match; ``other`` is read under
        its own lock first so the merge never holds both locks at once."""
        if other._bounds != self._bounds:
            raise ValueError("cannot merge histograms with different bounds")
        counts, total, sum_, max_, last = other.snapshot()
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._total += total
            self._sum += sum_
            if max_ > self._max:
                self._max = max_
            if total:
                self._last = last

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._total = 0
            self._sum = 0.0
            self._max = 0.0
            self._last = 0.0


# ---------------------------------------------------------------------------
# Request-scoped tracing
# ---------------------------------------------------------------------------

# The id of the HTTP request (or CLI run) the current thread is working
# for. contextvars propagate per-thread here: each server handler thread
# sets it on entry, so storage-op records and span() lines deep in the
# stack attribute themselves without any parameter threading.
_request_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "pio_request_id", default=None)

# wire-safe id: printable, header-friendly, bounded
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._\-]{1,128}$")


def current_request_id() -> Optional[str]:
    return _request_id.get()


def set_request_id(rid: Optional[str]) -> contextvars.Token:
    """Bind the current context to ``rid``; returns the token for
    :func:`reset_request_id`."""
    return _request_id.set(rid)


def reset_request_id(token: contextvars.Token) -> None:
    _request_id.reset(token)


def ensure_request_id(given: Optional[str] = None) -> str:
    """Accept a client-supplied ``X-Request-ID`` when it is wire-safe,
    else mint a fresh one (16 hex chars)."""
    if given and _REQUEST_ID_RE.match(given):
        return given
    return secrets.token_hex(8)


@contextlib.contextmanager
def request_scope(given: Optional[str] = None):
    """Context manager binding a request id for the block; yields the id."""
    rid = ensure_request_id(given)
    token = set_request_id(rid)
    try:
        yield rid
    finally:
        reset_request_id(token)


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str] = None):
    """Capture a jax.profiler trace of the block into ``trace_dir``
    (no-op when None). View with TensorBoard's profile plugin or
    Perfetto. Each capture is counted in the metrics registry
    (``pio_profile_traces_total``) and, as a side effect of the first
    call, installs the JIT-compile listener so compile count/time show
    up alongside the trace."""
    if not trace_dir:
        yield
        return
    from predictionio_tpu.utils import metrics

    metrics.install_jit_compile_listener()
    import jax

    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        yield
    metrics.PROFILE_TRACES.inc()
    logger.info("profiler trace written to %s (%.3fs)", trace_dir,
                time.perf_counter() - t0)


@contextlib.contextmanager
def span(name: str, level: int = logging.DEBUG,
         histogram: Optional[LatencyHistogram] = None):
    """Log the wall-clock duration of a block, tagged with the current
    request id (when one is bound) so concurrent servers produce
    attributable logs. ``histogram`` additionally records the duration
    (how the DASE-stage spans feed ``pio_train_stage_seconds``)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        took = time.perf_counter() - t0
        if histogram is not None:
            histogram.record(took)
        rid = current_request_id()
        if rid:
            logger.log(level, "%s took %.3fs [rid=%s]", name, took, rid)
        else:
            logger.log(level, "%s took %.3fs", name, took)
