"""Process-wide metrics registry with Prometheus + JSON exposition.

The reference's observability is a single running latency average in the
query server (``CreateServer.scala:438-440,623-630``) and per-app ingest
counters behind ``--stats`` (``Stats.scala``/``StatsActor.scala``);
everything else is "look at the Spark UI". This module is the TPU
build's substrate for first-class metrics:

- :class:`Counter` / :class:`Gauge` / :class:`Histogram` — labeled,
  thread-safe, registered in one process-wide :class:`MetricsRegistry`
  (histograms reuse :class:`~predictionio_tpu.utils.tracing.
  LatencyHistogram` as their sample store).
- Two renderers over the same state: :meth:`MetricsRegistry.
  render_prometheus` (text exposition: ``# HELP``/``# TYPE`` lines,
  cumulative ``le`` buckets, ``_sum``/``_count`` series) and
  :meth:`MetricsRegistry.snapshot` (JSON for ``/stats.json``). A
  differential test asserts the two always agree.
- A process-wide kill switch (:func:`set_enabled`, env ``PIO_METRICS=0``
  or the servers' ``--metrics off`` flag): disabled, every ``inc``/
  ``observe`` returns before touching a lock, so instrumentation can be
  benchmarked off (the < 5% overhead gate in the bench harness).
- :func:`install_jit_compile_listener` — wires ``jax.monitoring`` into
  the registry so XLA compile count/time show up next to the DASE-stage
  spans (the training-stall attribution ALX/TurboGR lean on).

Naming conventions (documented in README "Observability"): every metric
is ``pio_``-prefixed, durations are seconds, histograms are log-bucketed,
label values are low-cardinality (routes are patterns, never raw paths).
"""

from __future__ import annotations

import collections
import math
import os
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.utils.tracing import (
    LatencyHistogram,
    current_sampled_trace_id,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class MetricError(ValueError):
    pass


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    """Sample-value formatting: integers without a fraction, +Inf/-Inf
    spelled the Prometheus way."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    return repr(float(v))


def _pairs_str(pairs: Sequence[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{n}="{_escape_label_value(str(v))}"'
                     for n, v in pairs)
    return "{" + inner + "}"


def _label_str(names: Sequence[str], values: Sequence[str],
               extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [(n, v) for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(extra)
    return _pairs_str(pairs)


class _Metric:
    """One named metric family; children are per-label-set series."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 label_names: Sequence[str]):
        if not _NAME_RE.match(name):
            raise MetricError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise MetricError(f"invalid label name {ln!r} on {name}")
        self._registry = registry
        self.name = name
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise MetricError(
                f"{self.name} expects labels {self.label_names}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[n]) for n in self.label_names)

    def _new_child(self):
        raise NotImplementedError

    def _child(self, labels: Dict[str, str]):
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def labels(self, **labels: str):
        """Get-or-create the series for one label set."""
        return self._child(labels)

    def _items(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())

    def clear(self) -> None:
        with self._lock:
            self._children.clear()


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Metric):
    """Monotonic labeled counter."""

    kind = "counter"

    def _new_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if not self._registry.enabled:
            return
        self._child(labels).inc(amount)

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
        return 0.0 if child is None else child.value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Pull gauge: ``fn`` is called at scrape time (e.g. live queue
        depth) instead of pushing every transition."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            return float("nan")


class Gauge(_Metric):
    """Labeled gauge; supports push (set/inc/dec) and pull
    (set_function) styles."""

    kind = "gauge"

    def _new_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float, **labels: str) -> None:
        if not self._registry.enabled:
            return
        self._child(labels).set(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if not self._registry.enabled:
            return
        self._child(labels).inc(amount)

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn: Callable[[], float], **labels: str) -> None:
        # registered even when disabled: pull gauges are scrape-time only
        self._child(labels).set_function(fn)

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
        return 0.0 if child is None else child.value


class Histogram(_Metric):
    """Labeled histogram over :class:`LatencyHistogram` children."""

    kind = "histogram"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 label_names: Sequence[str],
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(registry, name, help, label_names)
        self._buckets = None if buckets is None else tuple(buckets)

    def _new_child(self) -> LatencyHistogram:
        return LatencyHistogram(bounds=self._buckets)

    def observe(self, value: float, **labels: str) -> None:
        if not self._registry.enabled:
            return
        # an active SAMPLED trace id rides along as the series'
        # exemplar, so a regressed histogram links straight to an
        # openable trace (an unsampled id would usually 404)
        self._child(labels).record(value,
                                   exemplar=current_sampled_trace_id())

    def time(self, **labels: str):
        """Context manager recording the block's wall time."""
        import contextlib
        import time as _time

        @contextlib.contextmanager
        def timer():
            t0 = _time.perf_counter()
            try:
                yield
            finally:
                self.observe(_time.perf_counter() - t0, **labels)
        return timer()

    def child(self, **labels: str) -> LatencyHistogram:
        """The underlying LatencyHistogram (e.g. for ``summary()``)."""
        return self._child(labels)


class MetricsRegistry:
    """Thread-safe name -> metric family registry.

    ``counter``/``gauge``/``histogram`` are get-or-create: calling twice
    with the same (name, kind, labels) returns the same family, so any
    module can declare the metrics it touches without import-order
    coupling; a redefinition with a DIFFERENT kind or label set is a
    programming error and raises.
    """

    def __init__(self, enabled: Optional[bool] = None):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        if enabled is None:
            enabled = os.environ.get("PIO_METRICS", "1").strip().lower() \
                not in ("0", "off", "false")
        self.enabled = bool(enabled)

    # -- declaration ------------------------------------------------------
    def _declare(self, cls, name: str, help: str,
                 label_names: Sequence[str], **kwargs) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.label_names != tuple(label_names)):
                    raise MetricError(
                        f"metric {name} already registered as "
                        f"{existing.kind}{existing.label_names}")
                if cls is Histogram:
                    want = kwargs.get("buckets")
                    want = None if want is None else tuple(want)
                    if existing._buckets != want:
                        # silently returning the first family would feed
                        # the second declarer's observations into the
                        # wrong bounds (e.g. minutes into a 5s-top scale)
                        raise MetricError(
                            f"histogram {name} already registered with "
                            f"buckets {existing._buckets}, redeclared "
                            f"with {want}")
                return existing
            metric = cls(self, name, help, label_names, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str,
                label_names: Sequence[str] = ()) -> Counter:
        return self._declare(Counter, name, help, label_names)

    def gauge(self, name: str, help: str,
              label_names: Sequence[str] = ()) -> Gauge:
        return self._declare(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str,
                  label_names: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._declare(Histogram, name, help, label_names,
                             buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every series (families stay declared) — test isolation."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()

    # -- renderers --------------------------------------------------------
    def _families(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def render_prometheus(self) -> str:
        """Text exposition format (version 0.0.4): ``# HELP``/``# TYPE``
        per family, cumulative ``le`` buckets + ``_sum``/``_count`` for
        histograms."""
        lines: List[str] = []
        for m in self._families():
            items = m._items()
            if not items:
                continue
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, child in items:
                if m.kind == "histogram":
                    counts, total, sum_, _mx, _last = child.snapshot()
                    bounds = child.bounds
                    for i, acc in enumerate(
                            LatencyHistogram.cumulate(counts)):
                        le = bounds[i] if i < len(bounds) else math.inf
                        ls = _label_str(m.label_names, key,
                                        extra=("le", _fmt_le(le)))
                        lines.append(f"{m.name}_bucket{ls} {acc}")
                    ls = _label_str(m.label_names, key)
                    lines.append(f"{m.name}_sum{ls} {repr(float(sum_))}")
                    lines.append(f"{m.name}_count{ls} {total}")
                else:
                    ls = _label_str(m.label_names, key)
                    lines.append(f"{m.name}{ls} {_fmt_value(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, Any]:
        """JSON view of the same state the Prometheus renderer exposes
        (``/stats.json``). Histogram series carry BOTH the cumulative
        ``le`` buckets (scrape parity) and the percentile summary."""
        out: Dict[str, Any] = {}
        for m in self._families():
            items = m._items()
            if not items:
                continue
            series = []
            for key, child in items:
                labels = dict(zip(m.label_names, key))
                if m.kind == "histogram":
                    counts, total, sum_, mx, last = child.snapshot()
                    buckets = []
                    bounds = child.bounds
                    for i, acc in enumerate(
                            LatencyHistogram.cumulate(counts)):
                        le = bounds[i] if i < len(bounds) else math.inf
                        buckets.append({"le": _fmt_le(le),
                                        "cumulative": acc})
                    entry = {
                        "labels": labels,
                        "count": total,
                        "sum": sum_,
                        "max": mx,
                        "last": last,
                        "buckets": buckets,
                        "summary": child.summary(),
                    }
                    ex = child.exemplar
                    if ex is not None:
                        entry["exemplar"] = {"traceId": ex[0],
                                             "value": ex[1]}
                    series.append(entry)
                else:
                    series.append({"labels": labels, "value": child.value})
            out[m.name] = {"type": m.kind, "help": m.help, "series": series}
        return out


# ---------------------------------------------------------------------------
# The process-wide registry + the metric families every layer shares
# ---------------------------------------------------------------------------

REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return REGISTRY


# ---------------------------------------------------------------------------
# Shareable (de)serialization entry points — fleet federation (PR 19)
# parses member expositions back into snapshot-shaped families and
# re-renders merged families; both directions live HERE so they can
# never drift from render_prometheus()/snapshot() above.
# ---------------------------------------------------------------------------

def _parse_label_block(line: str, start: int) -> Tuple[Dict[str, str], int]:
    """Parse ``{a="b",c="d"}`` starting at ``line[start] == '{'``;
    returns (labels, index just past the closing brace). Handles the
    text-format escapes (\\\\, \\", \\n) inside quoted values."""
    labels: Dict[str, str] = {}
    i = start + 1
    n = len(line)
    while i < n:
        while i < n and line[i] in ", ":
            i += 1
        if i < n and line[i] == "}":
            return labels, i + 1
        eq = line.find("=", i)
        if eq == -1:
            raise MetricError(f"unterminated label block: {line!r}")
        name = line[i:eq].strip()
        i = eq + 1
        if i >= n or line[i] != '"':
            raise MetricError(f"unquoted label value: {line!r}")
        i += 1
        buf: List[str] = []
        while i < n:
            ch = line[i]
            if ch == "\\" and i + 1 < n:
                nxt = line[i + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                i += 2
                continue
            if ch == '"':
                i += 1
                break
            buf.append(ch)
            i += 1
        else:
            raise MetricError(f"unterminated label value: {line!r}")
        labels[name] = "".join(buf)
    raise MetricError(f"unterminated label block: {line!r}")


def _parse_sample_value(text: str) -> float:
    text = text.strip().split()[0]
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    return float(text)


def parse_prometheus(text: str) -> Dict[str, Any]:
    """Inverse of :meth:`MetricsRegistry.render_prometheus`: parse a
    text exposition (version 0.0.4) into the same snapshot-shaped dict
    :meth:`MetricsRegistry.snapshot` produces, so federation can merge
    remote members with the local snapshot uniformly.

    Histogram ``max``/``last`` are not carried by the text format and
    parse as 0.0; summaries are omitted (the merged histogram is
    rebuilt through :class:`LatencyHistogram`, which recomputes them).
    Unparseable sample lines raise :class:`MetricError` — a skewed or
    garbage member should surface as a scrape problem, not as silently
    partial data."""
    helps: Dict[str, str] = {}
    kinds: Dict[str, str] = {}
    scalars: Dict[str, "collections.OrderedDict"] = {}
    hists: Dict[str, "collections.OrderedDict"] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):].split(None, 1)
            if rest:
                helps[rest[0]] = rest[1] if len(rest) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):].split(None, 1)
            if len(rest) == 2:
                kinds[rest[0]] = rest[1].strip()
            continue
        if line.startswith("#"):
            continue
        brace = line.find("{")
        sp = line.find(" ")
        if brace != -1 and (sp == -1 or brace < sp):
            name = line[:brace]
            labels, after = _parse_label_block(line, brace)
            value = _parse_sample_value(line[after:])
        else:
            if sp == -1:
                raise MetricError(f"malformed sample line: {line!r}")
            name = line[:sp]
            labels = {}
            value = _parse_sample_value(line[sp:])
        base = None
        part = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) \
                    and kinds.get(name[:-len(suffix)]) == "histogram":
                base, part = name[:-len(suffix)], suffix
                break
        if base is not None:
            fam = hists.setdefault(base, collections.OrderedDict())
            rest_labels = {k: v for k, v in labels.items() if k != "le"}
            key = tuple(sorted(rest_labels.items()))
            entry = fam.setdefault(key, {"labels": rest_labels,
                                         "count": 0, "sum": 0.0,
                                         "max": 0.0, "last": 0.0,
                                         "buckets": []})
            if part == "_bucket":
                if "le" not in labels:
                    raise MetricError(
                        f"histogram bucket without le: {line!r}")
                entry["buckets"].append({"le": labels["le"],
                                         "cumulative": int(value)})
            elif part == "_sum":
                entry["sum"] = float(value)
            else:
                entry["count"] = int(value)
            continue
        fam = scalars.setdefault(name, collections.OrderedDict())
        key = tuple(sorted(labels.items()))
        fam[key] = {"labels": labels, "value": value}
    out: Dict[str, Any] = {}
    for name in sorted(set(scalars) | set(hists)):
        if name in hists:
            series: List[Dict[str, Any]] = []
            for entry in hists[name].values():
                entry["buckets"].sort(
                    key=lambda b: float(b["le"].replace("+Inf", "inf")))
                series.append(entry)
            out[name] = {"type": "histogram",
                         "help": helps.get(name, ""), "series": series}
        else:
            out[name] = {"type": kinds.get(name, "untyped"),
                         "help": helps.get(name, ""),
                         "series": list(scalars[name].values())}
    return out


def histogram_from_snapshot(entry: Dict[str, Any]) -> LatencyHistogram:
    """Rebuild a :class:`LatencyHistogram` from one snapshot-shaped
    histogram series entry (cumulative ``le`` buckets). Raises
    :class:`MetricError` on malformed bucket sets (missing +Inf,
    non-monotonic cumulative counts) — federation reports these as
    member problems instead of merging garbage."""
    buckets = list(entry.get("buckets") or ())
    if not buckets:
        raise MetricError("histogram series has no buckets")
    bounds: List[float] = []
    cums: List[int] = []
    for b in buckets:
        le = str(b["le"])
        bounds.append(math.inf if le == "+Inf" else float(le))
        cums.append(int(b["cumulative"]))
    if not math.isinf(bounds[-1]):
        raise MetricError("histogram series is missing the +Inf bucket")
    counts: List[int] = []
    prev = 0
    for c in cums:
        if c < prev:
            raise MetricError(
                "histogram cumulative buckets must be non-decreasing")
        counts.append(c - prev)
        prev = c
    try:
        return LatencyHistogram.from_state(
            tuple(bounds[:-1]), counts, total=cums[-1],
            sum_sec=float(entry.get("sum", 0.0)),
            max_sec=float(entry.get("max", 0.0)),
            last_sec=float(entry.get("last", 0.0)))
    except ValueError as exc:
        raise MetricError(str(exc)) from exc


def histogram_snapshot_entry(hist: LatencyHistogram,
                             labels: Dict[str, str]) -> Dict[str, Any]:
    """One snapshot-shaped histogram series entry for ``hist`` —
    byte-identical in structure to :meth:`MetricsRegistry.snapshot`'s
    histogram entries (used for merged fleet series)."""
    counts, total, sum_, mx, last = hist.snapshot()
    bounds = hist.bounds
    buckets = []
    for i, acc in enumerate(LatencyHistogram.cumulate(counts)):
        le = bounds[i] if i < len(bounds) else math.inf
        buckets.append({"le": _fmt_le(le), "cumulative": acc})
    return {"labels": dict(labels), "count": total, "sum": sum_,
            "max": mx, "last": last, "buckets": buckets,
            "summary": hist.summary()}


def render_family_lines(name: str, kind: str,
                        series: Sequence[Dict[str, Any]],
                        extra: Optional[Tuple[str, str]] = None
                        ) -> List[str]:
    """Sample lines (no HELP/TYPE header) for snapshot-shaped series,
    matching :meth:`MetricsRegistry.render_prometheus` formatting.
    ``extra`` appends one more label pair to every sample — federation
    uses it to stamp ``member=`` on drill-down series."""
    lines: List[str] = []
    for entry in series:
        base = list((entry.get("labels") or {}).items())
        if extra is not None:
            base = base + [extra]
        if kind == "histogram":
            for b in entry.get("buckets") or ():
                pairs = base + [("le", str(b["le"]))]
                lines.append(
                    f"{name}_bucket{_pairs_str(pairs)}"
                    f" {int(b['cumulative'])}")
            ls = _pairs_str(base)
            lines.append(f"{name}_sum{ls} {repr(float(entry.get('sum', 0.0)))}")
            lines.append(f"{name}_count{ls} {int(entry.get('count', 0))}")
        else:
            lines.append(
                f"{name}{_pairs_str(base)}"
                f" {_fmt_value(float(entry.get('value', 0.0)))}")
    return lines


def set_enabled(enabled: bool) -> None:
    """Process-wide instrumentation switch (``--metrics on|off`` /
    ``PIO_METRICS``). Disabled, every inc/observe returns before taking
    a lock; declared families and live series stay readable."""
    REGISTRY.enabled = bool(enabled)


# power-of-two-ish counts for batch sizes / queue depths
COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0)

# long-running work (training stages): seconds to hours — the default
# latency bounds top out at 5s and would collapse real stage times into
# the +Inf bucket
LONG_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0,
                1800.0, 7200.0)

# -- HTTP serving (event server + query server) ----------------------------
HTTP_REQUESTS = REGISTRY.counter(
    "pio_http_requests_total",
    "HTTP requests by server, route pattern, method and status code",
    ("server", "route", "method", "status"))
HTTP_LATENCY = REGISTRY.histogram(
    "pio_http_request_seconds",
    "End-to-end HTTP request latency by server and route pattern",
    ("server", "route"))

# -- ingest (event server) -------------------------------------------------
INGEST_EVENTS = REGISTRY.counter(
    "pio_ingest_events_total",
    "Ingested events by app, event type and response status",
    ("app_id", "event", "status"))

# -- query serving ---------------------------------------------------------
QUERY_LATENCY = REGISTRY.histogram(
    "pio_query_seconds",
    "Query-path latency (extract+predict+serve) per engine variant",
    ("variant",))
MICROBATCH_QUERIES = REGISTRY.counter(
    "pio_microbatch_queries_total",
    "Queries served through a micro-batched device dispatch",
    ("batcher",))
MICROBATCH_DISPATCHES = REGISTRY.counter(
    "pio_microbatch_dispatches_total",
    "Device dispatches issued by the micro-batcher",
    ("batcher",))
MICROBATCH_QUEUE_DEPTH = REGISTRY.gauge(
    "pio_microbatch_queue_depth",
    "Requests currently waiting in the micro-batcher queue",
    ("batcher",))
MICROBATCH_BATCH_SIZE = REGISTRY.histogram(
    "pio_microbatch_batch_size",
    "Queries merged into one device dispatch",
    ("batcher",), buckets=COUNT_BUCKETS)
MICROBATCH_TRIGGERS = REGISTRY.counter(
    "pio_microbatch_dispatch_triggers_total",
    "Dispatches by what formed the batch (size = max_batch reached; "
    "window = the oldest query's PIO_BATCH_WINDOW budget expired; "
    "drain = shutdown flush)",
    ("batcher", "trigger"))
# fill ratio needs its own bounds: COUNT_BUCKETS are absolute sizes,
# but a half-full 256-batch and a half-full 8-batch mean the same thing
FILL_BUCKETS = (0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
MICROBATCH_FILL = REGISTRY.histogram(
    "pio_microbatch_fill_ratio",
    "Dispatched batch size as a fraction of the lane's max_batch",
    ("batcher",), buckets=FILL_BUCKETS)
MICROBATCH_QUEUE_AT_DISPATCH = REGISTRY.histogram(
    "pio_microbatch_queue_depth_at_dispatch",
    "Pending queue depth observed at each dispatch (the percentile "
    "source for batcher_stats queueDepthPercentiles)",
    ("batcher",), buckets=COUNT_BUCKETS)

# -- storage ---------------------------------------------------------------
# ``shard`` is empty for direct (single-store) DAOs; the fleet router
# stamps it with the shard index on the per-shard legs it issues, so one
# slow or failing shard is visible inside the fan-out.
STORAGE_OP_LATENCY = REGISTRY.histogram(
    "pio_storage_op_seconds",
    "Event-store DAO operation latency by backend, op and shard",
    ("backend", "op", "shard"))
STORAGE_OP_ERRORS = REGISTRY.counter(
    "pio_storage_op_errors_total",
    "Event-store DAO operation failures by backend, op, error class "
    "and shard",
    ("backend", "op", "error", "shard"))

# -- resilience (retries, breakers, degradation, fault injection) ----------
STORAGE_RETRIES = REGISTRY.counter(
    "pio_storage_retries_total",
    "Storage-op retry attempts by backend and op (each retry masked one "
    "transient failure)",
    ("backend", "op"))
CIRCUIT_STATE = REGISTRY.gauge(
    "pio_circuit_state",
    "Circuit-breaker state per endpoint (0 closed, 1 open, 2 half-open)",
    ("endpoint",))
CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "pio_circuit_transitions_total",
    "Circuit-breaker state transitions by endpoint and target state",
    ("endpoint", "to"))
DEGRADED_QUERIES = REGISTRY.counter(
    "pio_degraded_queries_total",
    "Queries answered in degraded mode (storage down / breaker open / "
    "read timed out) instead of failing",
    ("reason",))
FEEDBACK_DROPPED = REGISTRY.counter(
    "pio_feedback_dropped_total",
    "Feedback-loop predict events dropped after the bounded retry", ())
MICROBATCH_REJECTIONS = REGISTRY.counter(
    "pio_microbatch_rejections_total",
    "Queries rejected (503 + Retry-After) after waiting past the "
    "micro-batcher queue deadline",
    ("batcher",))
FAULTS_INJECTED = REGISTRY.counter(
    "pio_faults_injected_total",
    "Faults fired by the PIO_FAULTS deterministic injection harness",
    ("backend", "op", "kind"))

# -- materialized entity-property aggregation (PR 1) -----------------------
AGGREGATE_HITS = REGISTRY.counter(
    "pio_aggregate_hits_total",
    "aggregate_properties reads served from materialized state",
    ("backend",))
AGGREGATE_REPLAYS = REGISTRY.counter(
    "pio_aggregate_replays_total",
    "aggregate_properties reads that replayed event history "
    "(bounded = time-travel query; fallback = no/failed materialized state)",
    ("backend", "reason"))
AGGREGATE_BACKFILLS = REGISTRY.counter(
    "pio_aggregate_backfills_total",
    "Materialized-aggregation scope backfills (full history refolds)",
    ("backend",))
AGGREGATE_SCOPE_DROPS = REGISTRY.counter(
    "pio_aggregate_scope_drops_total",
    "Materialized-aggregation scope invalidations (partition rewrites, "
    "bulk deletes, app removals)",
    ("backend",))

# -- batch prediction ------------------------------------------------------
BATCHPREDICT_QUERIES = REGISTRY.counter(
    "pio_batchpredict_queries_total",
    "Batch-prediction queries by outcome (scored = computed this run; "
    "skipped = chunk already complete in the manifest)",
    ("status",))
BATCHPREDICT_CHUNK_LATENCY = REGISTRY.histogram(
    "pio_batchpredict_chunk_seconds",
    "Wall time to score and persist one batch-prediction chunk")
BATCHPREDICT_QPS = REGISTRY.gauge(
    "pio_batchpredict_queries_per_sec",
    "Scoring throughput of the most recent batch-prediction run")

# -- online fold-in (PR 8) -------------------------------------------------
# event-ingested -> reflected-in-top-k can legitimately span the fold
# cadence (seconds), which the default latency bounds would collapse
# into +Inf
FRESHNESS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0,
                     60.0)
FOLDIN_FOLDS = REGISTRY.counter(
    "pio_foldin_folds_total",
    "Online fold-in batches by outcome (ok / error / dropped)",
    ("status",))
FOLDIN_TAIL_ERRORS = REGISTRY.counter(
    "pio_foldin_tail_errors_total",
    "Failed tail reads (one per failing poll; pio_foldin_stale holds 1 "
    "for the duration of the outage)", ())
FOLDIN_USERS = REGISTRY.counter(
    "pio_foldin_users_total",
    "User rows patched into the live factor store by the fold-in "
    "consumer (known = re-solved existing rows; new = store grown)",
    ("kind",))
FOLDIN_EVENTS = REGISTRY.counter(
    "pio_foldin_events_total",
    "Rating events consumed from the tail read and folded", ())
FOLDIN_FRESHNESS = REGISTRY.histogram(
    "pio_foldin_freshness_seconds",
    "Event ingested -> factors servable latency per folded event",
    buckets=FRESHNESS_BUCKETS)
FOLDIN_STALE = REGISTRY.gauge(
    "pio_foldin_stale",
    "1 while the fold-in tail read is failing (serving continues from "
    "the last-good factors, responses carry degradedReasons "
    "foldin_stale)", ())

# -- device-plane telemetry (PR 12) ----------------------------------------
# device dispatches are sub-millisecond on a healthy accelerator; the
# default latency bounds' 0.5ms floor would collapse every fused-lane
# dispatch into one bucket
DEVICE_DISPATCH_BUCKETS = (0.000025, 0.00005, 0.0001, 0.00025, 0.0005,
                           0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                           0.5, 2.0)
DISPATCH_DEVICE_SECONDS = REGISTRY.histogram(
    "pio_dispatch_device_seconds",
    "Device time per serving dispatch (dispatch -> block_until_ready on "
    "the monotonic clock) by lane, kernel family and store precision",
    ("lane", "kernel", "precision"), buckets=DEVICE_DISPATCH_BUCKETS)
AOT_CACHE_REQUESTS = REGISTRY.counter(
    "pio_aot_cache_requests_total",
    "Serving-program lookups against the AOT bucket ladder (hit = "
    "precompiled executable; miss_jit = jit fallback, e.g. a store "
    "reshaped by fold-in growth before the next warmup)",
    ("result",))
AOT_CACHE_EVICTIONS = REGISTRY.counter(
    "pio_aot_cache_evictions_total",
    "AOT executables evicted from a bounded cache (a rising rate under "
    "fold-in growth is a recompile storm, not a mystery)", ())
DEVICE_STORE_BYTES = REGISTRY.gauge(
    "pio_device_store_bytes",
    "HBM bytes pinned by live device factor stores (factors + scales + "
    "seen tables + normalized item matrix, across all live servers)", ())
AOT_LADDER_BYTES = REGISTRY.gauge(
    "pio_aot_ladder_bytes",
    "Estimated bytes held by AOT-compiled serving ladder executables "
    "(memory_analysis over every compiled entry; 0 where the backend "
    "has no stats)", ())
PROFILE_CAPTURES_ACTIVE = REGISTRY.gauge(
    "pio_profile_capture_active",
    "1 while an on-demand jax.profiler capture (POST /profile/start) "
    "is running", ())

# -- training workflow -----------------------------------------------------
TRAIN_STAGE_LATENCY = REGISTRY.histogram(
    "pio_train_stage_seconds",
    "DASE pipeline stage wall time (read/prepare/train/eval)",
    ("stage",), buckets=LONG_BUCKETS)
JIT_COMPILES = REGISTRY.counter(
    "pio_jit_compiles_total",
    "XLA compilations observed via jax.monitoring", ())
JIT_COMPILE_SECONDS = REGISTRY.counter(
    "pio_jit_compile_seconds_total",
    "Cumulative XLA compile wall time via jax.monitoring", ())
PROFILE_TRACES = REGISTRY.counter(
    "pio_profile_traces_total",
    "jax.profiler traces captured by profile_trace", ())
TRAIN_DIVERGED = REGISTRY.counter(
    "pio_train_diverged_total",
    "Training runs aborted by the per-chunk non-finite factor guard "
    "(the last intact checkpoint is retained)", ())
TRAIN_CHECKPOINTS = REGISTRY.counter(
    "pio_train_checkpoints_total",
    "Training-checkpoint events by outcome (saved / resumed / "
    "torn_skipped)", ("status",))
TRAIN_LOSS = REGISTRY.gauge(
    "pio_train_loss",
    "Latest on-device training-objective sample by component "
    "(fit / l2 / total); on the vmapped grid lane the best alive "
    "config's sample", ("component",))
TRAIN_CHUNK_SECONDS = REGISTRY.histogram(
    "pio_train_chunk_seconds",
    "Wall time of one checkpoint chunk (iteration scan + objective "
    "sample + checkpoint write)", (), buckets=LONG_BUCKETS)


class BoundedLabel:
    """Cap the distinct values a CLIENT-CONTROLLED label may mint.

    Series live for the process lifetime, so a label fed from request
    data (e.g. event names) would otherwise be an unbounded-memory lever
    for any client with an access key. The first ``cap`` distinct values
    keep their identity; everything after collapses to ``overflow``.
    """

    def __init__(self, cap: int = 100, overflow: str = "<other>"):
        self._cap = int(cap)
        self._overflow = overflow
        self._seen: set = set()
        self._lock = threading.Lock()

    def __call__(self, value: str) -> str:
        v = str(value)
        with self._lock:
            if v in self._seen:
                return v
            if len(self._seen) < self._cap:
                self._seen.add(v)
                return v
        return self._overflow


_jit_listener_lock = threading.Lock()
_jit_listener_installed = False


def install_jit_compile_listener() -> bool:
    """Register a ``jax.monitoring`` duration listener feeding the
    JIT-compile counters (idempotent; False when the running jax has no
    monitoring API). The listener is a no-op while the registry is
    disabled, so installing it does not tax a metrics-off process."""
    global _jit_listener_installed
    with _jit_listener_lock:
        if _jit_listener_installed:
            return True
        try:
            from jax import monitoring as _monitoring
            register = _monitoring.register_event_duration_secs_listener
        except (ImportError, AttributeError):
            return False

        def _on_duration(event: str, duration: float, **kwargs) -> None:
            if not REGISTRY.enabled:
                return
            if "compile" in event:
                JIT_COMPILES.inc()
                JIT_COMPILE_SECONDS.inc(max(0.0, float(duration)))

        register(_on_duration)
        _jit_listener_installed = True
        return True
