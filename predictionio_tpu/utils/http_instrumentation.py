"""Shared HTTP-handler instrumentation for the stdlib servers.

Both daemons (event server ``data/api/event_server.py``, query server
``workflow/create_server.py``) mount this mixin on their
``BaseHTTPRequestHandler`` so request-id handling, response plumbing and
per-route accounting stay identical by construction:

- ``_dispatch_instrumented`` binds the request id (accepted from
  ``X-Request-ID`` or minted) into the tracing contextvar, times the
  request, and accounts it under ``pio_http_requests_total`` /
  ``pio_http_request_seconds`` with the subclass's server label and
  route pattern.
- ``_respond`` / ``_respond_bytes`` echo the request id and record the
  status the accounting reads.
- ``_respond_prometheus`` serves the registry's text exposition.

Subclasses set ``metrics_server_label`` and override ``_route_label``
(route PATTERNS only — an id or client-chosen name must never mint a
new series).
"""

from __future__ import annotations

import json
import time
from typing import Any, Optional

from predictionio_tpu.utils import metrics
from predictionio_tpu.utils.tracing import (
    ensure_request_id,
    reset_request_id,
    set_request_id,
)


class InstrumentedHandlerMixin:
    """Request-id + metrics plumbing over BaseHTTPRequestHandler."""

    metrics_server_label = "unknown"  # subclass overrides

    def _route_label(self, path: str) -> str:  # subclass overrides
        return "<other>"

    # -- responses ---------------------------------------------------------
    def _respond(self, status: int, payload: Any) -> None:
        self._respond_bytes(status, json.dumps(payload).encode("utf-8"),
                            "application/json; charset=UTF-8")

    def _respond_bytes(self, status: int, body: bytes,
                       content_type: str) -> None:
        self._status_sent = status
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid:  # echo the request id for client-side correlation
            self.send_header("X-Request-ID", rid)
        self.end_headers()
        self.wfile.write(body)

    def _respond_prometheus(self) -> None:
        self._respond_bytes(
            200, metrics.registry().render_prometheus().encode("utf-8"),
            "text/plain; version=0.0.4; charset=utf-8")

    # -- dispatch shell ----------------------------------------------------
    def _dispatch_instrumented(self, method: str, path: str,
                               handle) -> None:
        """Run ``handle()`` with the request id bound, then account the
        request under its route pattern."""
        self._request_id = ensure_request_id(
            self.headers.get("X-Request-ID"))
        self._status_sent: Optional[int] = None
        token = set_request_id(self._request_id)
        t0 = time.perf_counter()
        try:
            handle()
        finally:
            reset_request_id(token)
            route = self._route_label(path)
            metrics.HTTP_LATENCY.observe(
                time.perf_counter() - t0,
                server=self.metrics_server_label, route=route)
            metrics.HTTP_REQUESTS.inc(
                server=self.metrics_server_label, route=route,
                method=method, status=str(self._status_sent or 0))
