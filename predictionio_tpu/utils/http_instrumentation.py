"""Shared HTTP-handler instrumentation for the stdlib servers.

All four daemons (event server ``data/api/event_server.py``, query
server ``workflow/create_server.py``, admin server
``tools/admin_server.py``, dashboard ``tools/dashboard.py``) mount this
mixin on their ``BaseHTTPRequestHandler`` so request-id handling, trace
propagation, response plumbing and per-route accounting stay identical
by construction:

- ``_dispatch_instrumented`` binds the request id (accepted from
  ``X-Request-ID`` or minted) into the tracing contextvar, opens a
  server span for the request — joining the caller's trace when a W3C
  ``traceparent`` header is present, minting a fresh head-sampled trace
  otherwise — times the request, and accounts it under
  ``pio_http_requests_total`` / ``pio_http_request_seconds`` with the
  subclass's server label and route pattern. The server span carries
  method/path/status attributes and flags 5xx responses as errors, so
  slow or failing requests land in the always-keep lane of the trace
  buffer (the slow-query log).
- ``_respond`` / ``_respond_bytes`` echo the request id AND the
  ``traceparent`` of the server span, and record the status the
  accounting reads.
- ``_respond_prometheus`` serves the registry's text exposition;
  ``_respond_traces_index`` / ``_respond_trace`` serve the trace
  buffer (``GET /traces.json``, ``GET /traces/<id>`` — plain span
  tree, ``?format=perfetto`` Chrome-trace-event JSON, ``?format=html``
  timeline).

Subclasses set ``metrics_server_label`` and override ``_route_label``
(route PATTERNS only — an id or client-chosen name must never mint a
new series).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List, Mapping, Optional

from predictionio_tpu.utils import metrics, tracing
from predictionio_tpu.utils.tracing import (
    ensure_request_id,
    reset_request_id,
    set_request_id,
)


class SeveringThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose ``server_close`` also severs every
    ESTABLISHED connection. The stock server only closes the listening
    socket: established keep-alive connections stay serviceable by
    their handler threads, so an in-process "stopped" server keeps
    answering pooled clients — a dead host would not. Severing makes
    ``stop()`` mean what a host death means, which the blackout /
    dead-shard suites (and any client with a connection pool) rely on.
    Idle keep-alive connections see a clean EOF; only a request caught
    mid-flight gets a reset, exactly like a real crash."""

    def __init__(self, *args, **kwargs):
        self._live_conns: set = set()
        self._live_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def get_request(self):
        sock, addr = super().get_request()
        with self._live_lock:
            self._live_conns.add(sock)
        return sock, addr

    def shutdown_request(self, request):
        with self._live_lock:
            self._live_conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._live_lock:
            conns = list(self._live_conns)
            self._live_conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class InstrumentedHandlerMixin:
    """Request-id + trace + metrics plumbing over BaseHTTPRequestHandler."""

    metrics_server_label = "unknown"  # subclass overrides

    # headers and body go out as separate small writes; with Nagle on,
    # the body segment waits for the headers segment's (delayed) ACK —
    # a flat ~40ms floor under every keep-alive request on Linux
    disable_nagle_algorithm = True

    def _route_label(self, path: str) -> str:  # subclass overrides
        return "<other>"

    # -- responses ---------------------------------------------------------
    def _respond(self, status: int, payload: Any) -> None:
        self._respond_bytes(status, json.dumps(payload).encode("utf-8"),
                            "application/json; charset=UTF-8")

    def _respond_bytes(self, status: int, body: bytes,
                       content_type: str,
                       extra_headers: Optional[Mapping[str, str]] = None
                       ) -> None:
        self._status_sent = status
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        rid = getattr(self, "_request_id", None)
        if rid:  # echo the request id for client-side correlation
            self.send_header("X-Request-ID", rid)
        tp = getattr(self, "_traceparent", None)
        if tp:  # echo the trace context the request ran under
            self.send_header("traceparent", tp)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _respond_prometheus(self) -> None:
        self._respond_bytes(
            200, metrics.registry().render_prometheus().encode("utf-8"),
            "text/plain; version=0.0.4; charset=utf-8")

    def _respond_healthz(self, checks: Mapping[str, bool]) -> None:
        """``GET /healthz`` — liveness + readiness in one probe, the
        same shape on all four servers. Answering at all IS liveness;
        readiness is the AND of the server's checks (deployment
        loaded, storage breaker closed, ...), with 503 telling the
        load balancer to route elsewhere while the process stays up."""
        checks = {k: bool(v) for k, v in checks.items()}
        ready = all(checks.values())
        # pid lets a fleet scraper tell a remote member from an
        # in-process one (tests/benches), which shares this process's
        # registry and must not be double-counted in federation
        self._respond(200 if ready else 503,
                      {"alive": True, "ready": ready, "checks": checks,
                       "server": self.metrics_server_label,
                       "pid": os.getpid()})

    # -- trace endpoints ---------------------------------------------------
    @staticmethod
    def _q_first(query: Optional[Dict[str, List[str]]], key: str
                 ) -> Optional[str]:
        vals = (query or {}).get(key)
        return vals[0] if vals else None

    def _respond_traces_index(
            self, query: Optional[Dict[str, List[str]]] = None) -> None:
        """GET /traces.json — recent retained traces + the slow-query
        log. An operator surface like /metrics (same exposure rules)."""
        buf = tracing.trace_buffer()
        try:
            limit = min(int(self._q_first(query, "limit") or 50), 500)
        except ValueError:
            limit = 50
        self._respond(200, {
            "enabled": buf.enabled,
            "sampleRate": buf.sample_rate,
            "slowThresholdSec": buf.slow_threshold_sec,
            "traces": buf.index(limit),
            "slowLog": buf.slow_log(limit),
        })

    def _respond_trace(self, trace_id: str,
                       query: Optional[Dict[str, List[str]]] = None
                       ) -> None:
        """GET /traces/<id> — this process's fragment of one trace:
        span tree JSON by default, ``?format=perfetto`` (or ``chrome``)
        for the Perfetto-loadable export, ``?format=html`` timeline."""
        rec = tracing.trace_buffer().get(trace_id)
        if rec is None:
            self._respond(404, {"message": f"trace {trace_id} not found"})
            return
        self._respond_trace_record(rec, query)

    def _respond_trace_record(
            self, rec: Dict[str, Any],
            query: Optional[Dict[str, List[str]]] = None) -> None:
        """Render an already-resolved trace record in the requested
        format (shared by the per-process lookup above and the
        balancer's fleet-assembled ``GET /traces/<id>``)."""
        fmt = self._q_first(query, "format") or "tree"
        if fmt in ("perfetto", "chrome"):
            self._respond(200, tracing.trace_to_chrome(rec))
        elif fmt == "html":
            self._respond_bytes(
                200, tracing.render_trace_html(rec).encode("utf-8"),
                "text/html; charset=utf-8")
        else:
            self._respond(200, rec)

    # status and observability surfaces never MINT traces: a 15s
    # Prometheus scrape, a load-balancer GET / probe or a `pio trace`
    # poll would otherwise fill the bounded ring and evict the traces
    # worth keeping. A caller who SENDS a traceparent is explicitly
    # tracing, so these routes still join an existing trace (retention
    # then rides the caller's sampling decision).
    _UNTRACED_ROUTES = ("/", "/healthz", "/metrics", "/stats.json",
                        "/dispatches.json", "/traces.json",
                        "/traces/<id>")

    # -- dispatch shell ----------------------------------------------------
    def _dispatch_instrumented(self, method: str, path: str,
                               handle) -> None:
        """Run ``handle()`` with the request id and a server trace span
        bound, then account the request under its route pattern."""
        self._request_id = ensure_request_id(
            self.headers.get("X-Request-ID"))
        self._status_sent: Optional[int] = None
        self._traceparent: Optional[str] = None
        parent = tracing.parse_traceparent(self.headers.get("traceparent"))
        route = self._route_label(path)
        token = set_request_id(self._request_id)
        t0 = time.perf_counter()
        try:
            if route in self._UNTRACED_ROUTES and parent is None:
                handle()
                return
            with tracing.trace_scope(
                    f"{self.metrics_server_label} {method} {route}",
                    parent=parent,
                    attributes={"method": method, "path": path,
                                "server": self.metrics_server_label,
                                "requestId": self._request_id}) as sp:
                self._traceparent = tracing.current_traceparent()
                try:
                    handle()
                finally:
                    if sp is not None:
                        status = self._status_sent or 0
                        sp.attributes["status"] = status
                        if status >= 500:
                            sp.error = True
        finally:
            reset_request_id(token)
            metrics.HTTP_LATENCY.observe(
                time.perf_counter() - t0,
                server=self.metrics_server_label, route=route)
            metrics.HTTP_REQUESTS.inc(
                server=self.metrics_server_label, route=route,
                method=method, status=str(self._status_sent or 0))
