"""Fault-tolerance primitives: retries, circuit breakers, degradation.

The reference's Spray/akka stack got supervision and bounded retries
from the actor runtime for free; the stdlib-threaded rebuild had NONE —
one dropped connection on the storage wire was a 500, a hung event
store was a 60s stall. This module is the substrate every remote hop
and serving path now shares:

- :class:`RetryPolicy` — exponential backoff with FULL jitter
  (AWS-style: ``delay = uniform(0, min(cap, base * 2**attempt))``), a
  per-op deadline budget so retries never stretch an op past its
  latency contract, and retry *classification*: failures that provably
  happened before the server saw the request (connection refused)
  retry anything; ambiguous failures (timeouts, 5xx, reset mid-flight)
  retry reads and idempotent writes only — a non-idempotent write
  retries solely when the caller supplied an idempotency key
  (client-generated event ids on the storage wire).
- :class:`CircuitBreaker` — per-endpoint closed → open on
  consecutive-failure count or windowed error rate, half-open probes
  after ``reset_timeout``, close on probe success. Only
  *transient-class* failures trip it (a 400 is the caller's bug, not
  the endpoint's health). Every state transition is counted
  (``pio_circuit_transitions_total``), gauged
  (``pio_circuit_state``) and emitted as a trace span.
- Degradation context — :func:`degraded_scope` /
  :func:`mark_degraded`: a serving layer opens a scope per query;
  storage layers that swallow a failure (timeout, breaker open) mark
  it; the server stamps ``degraded: true`` on the response instead of
  500ing. Serving a stale answer beats serving an error page.

Kill switch: ``PIO_RESILIENCE=0`` (or :func:`set_enabled`) bypasses
retry + breaker logic entirely — the overhead lane of
``bench.py::chaos_serving_bench`` measures against it.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("pio.resilience")

# -- retry classification ---------------------------------------------------
#
# SAFE:      the request provably never executed (TCP connect refused,
#            breaker said no before dialing) — retry ANY op.
# AMBIGUOUS: the op may or may not have executed (timeout, connection
#            reset mid-flight, HTTP 5xx) — retry reads and idempotent
#            writes; non-idempotent writes only with an idempotency key.
# PERMANENT: retrying cannot help (4xx, validation, programming errors).

SAFE = "safe"
AMBIGUOUS = "ambiguous"
PERMANENT = "permanent"

# OSError subclasses that are filesystem/programming facts, not
# transient network weather — never worth a retry
_PERMANENT_OSERRORS = (FileNotFoundError, FileExistsError,
                       PermissionError, IsADirectoryError,
                       NotADirectoryError)


def classify(exc: BaseException) -> str:
    """Retry class of one failure. An exception may pin its own class
    via a ``pio_retry_class`` attribute (the storage wire and the fault
    injector do); otherwise network-shaped ``OSError``\\ s are transient
    and everything else is permanent."""
    pinned = getattr(exc, "pio_retry_class", None)
    if pinned in (SAFE, AMBIGUOUS, PERMANENT):
        return pinned
    if isinstance(exc, ConnectionRefusedError):
        return SAFE  # TCP said no: the server never saw the request
    if isinstance(exc, _PERMANENT_OSERRORS):
        return PERMANENT
    if isinstance(exc, (TimeoutError, ConnectionError, OSError)):
        return AMBIGUOUS
    return PERMANENT


def retry_after_hint(exc: BaseException) -> Optional[float]:
    """Server-suggested backoff floor (``Retry-After``), if the failure
    carried one."""
    v = getattr(exc, "pio_retry_after", None)
    try:
        return None if v is None else max(0.0, float(v))
    except (TypeError, ValueError):
        return None


def is_transient(exc: BaseException) -> bool:
    return classify(exc) in (SAFE, AMBIGUOUS)


# -- kill switch ------------------------------------------------------------

_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        with _enabled_lock:
            if _enabled is None:
                _enabled = os.environ.get(
                    "PIO_RESILIENCE", "1").strip().lower() not in (
                        "0", "off", "false")
    return _enabled


def set_enabled(on: bool) -> None:
    """Process-wide retry/breaker switch (benchmark + test lever)."""
    global _enabled
    with _enabled_lock:
        _enabled = bool(on)


# -- RetryPolicy ------------------------------------------------------------


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("%s=%r is not a number; using %s", name, raw,
                       default)
        return default


class RetryPolicy:
    """Bounded retries with full-jitter exponential backoff.

    ``max_retries`` counts RE-tries (0 = single attempt). The deadline
    is a per-op budget from the FIRST attempt's start: a retry whose
    backoff would land past it is not taken — the op fails with the
    last error instead of silently stretching its latency contract.
    ``rng`` and ``sleep`` are injectable for deterministic tests.
    """

    def __init__(self, max_retries: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, deadline: Optional[float] = 30.0,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        self.max_retries = max(0, int(max_retries))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = deadline if deadline is None else float(deadline)
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._clock = clock

    @classmethod
    def from_env(cls, default_deadline: float = 30.0) -> "RetryPolicy":
        """``PIO_STORAGE_RETRIES`` / ``PIO_STORAGE_RETRY_BASE`` /
        ``PIO_STORAGE_RETRY_MAX`` / ``PIO_STORAGE_OP_DEADLINE``
        (seconds; deadline <= 0 disables the budget).

        ``default_deadline`` applies only when ``PIO_STORAGE_OP_DEADLINE``
        is unset: a caller whose single attempt can legitimately run
        long (the wire's read timeout) must raise it, or the budget is
        spent before the first retry and the timeout-retry lane is
        dead by construction."""
        deadline: Optional[float] = _env_float("PIO_STORAGE_OP_DEADLINE",
                                               default_deadline)
        if deadline is not None and deadline <= 0:
            deadline = None
        return cls(
            max_retries=int(_env_float("PIO_STORAGE_RETRIES", 3)),
            base_delay=_env_float("PIO_STORAGE_RETRY_BASE", 0.05),
            max_delay=_env_float("PIO_STORAGE_RETRY_MAX", 2.0),
            deadline=deadline)

    # a server-sent Retry-After FLOORS the backoff past max_delay (the
    # server knows its own pacing better than our jitter curve), but a
    # buggy/hostile header must not park the client arbitrarily long
    # when no deadline budget is set
    RETRY_AFTER_CAP = 60.0

    def backoff(self, attempt: int,
                floor: Optional[float] = None) -> float:
        """Full-jitter delay before retry number ``attempt + 1``; a
        server-sent ``Retry-After`` acts as the floor (the deadline
        budget, when set, still bounds the total)."""
        cap = min(self.max_delay, self.base_delay * (2 ** attempt))
        delay = self._rng.uniform(0.0, cap)
        if floor is not None:
            delay = max(delay, min(floor, self.RETRY_AFTER_CAP))
        return delay

    def run(self, fn: Callable[[int], Any], *, idempotent: Any = True,
            on_retry: Optional[Callable[[int, BaseException, float],
                                        None]] = None) -> Any:
        """Run ``fn(attempt)`` under the policy. ``fn`` receives the
        attempt index (0-based) so callers can tag retried requests
        (e.g. the idempotency-retry header on the storage wire).

        ``idempotent`` may be a bool or a zero-arg callable evaluated
        LAZILY at the first retry decision (and cached) — callers whose
        idempotency check costs something (parsing a bulk payload for
        idempotency keys) pay it only when a retry is actually on the
        table, never on the success path."""
        start = self._clock()
        attempt = 0
        idem: Optional[bool] = idempotent if isinstance(idempotent, bool) \
            else None
        while True:
            try:
                return fn(attempt)
            except BaseException as e:
                cls = classify(e)
                if idem is None and cls == AMBIGUOUS:
                    idem = bool(idempotent())
                retryable = cls == SAFE or (cls == AMBIGUOUS and idem)
                if not retryable or attempt >= self.max_retries:
                    raise
                delay = self.backoff(attempt, retry_after_hint(e))
                if self.deadline is not None and \
                        self._clock() - start + delay > self.deadline:
                    raise  # budget exhausted: fail with the real error
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                if delay > 0:
                    self._sleep(delay)
                attempt += 1


# -- CircuitBreaker ---------------------------------------------------------


class CircuitOpenError(RuntimeError):
    """Fail-fast refusal: the endpoint's breaker is open. Carries the
    time until the next half-open probe as the retry hint; classified
    PERMANENT so retry loops don't spin against an open breaker."""

    pio_retry_class = PERMANENT

    def __init__(self, endpoint: str, retry_in: float):
        super().__init__(
            f"circuit breaker open for {endpoint} "
            f"(next probe in {retry_in:.1f}s)")
        self.endpoint = endpoint
        self.pio_retry_after = max(0.0, retry_in)


CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class CircuitBreaker:
    """Per-endpoint availability guard.

    - CLOSED: calls pass; ``failure_threshold`` consecutive transient
      failures — or a windowed error rate ≥ ``error_rate`` over at
      least ``min_calls`` of the last ``window`` outcomes — opens it.
    - OPEN: ``before_call`` raises :class:`CircuitOpenError` until
      ``reset_timeout`` elapses, then exactly ONE caller is admitted
      as the half-open probe.
    - HALF_OPEN: probe success closes; probe failure re-opens (timer
      restarts).

    Only transient-class failures count (:func:`classify`): a client
    bug (400, validation) says nothing about endpoint health.
    """

    def __init__(self, endpoint: str, failure_threshold: int = 5,
                 reset_timeout: float = 5.0, window: int = 20,
                 error_rate: float = 0.5, min_calls: int = 10,
                 clock: Callable[[], float] = time.monotonic):
        self.endpoint = endpoint
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout = float(reset_timeout)
        self.window = max(1, int(window))
        self.error_rate = float(error_rate)
        self.min_calls = max(1, int(min_calls))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._outcomes: List[bool] = []  # rolling ok/fail window
        self._opened_at = 0.0
        self._probe_out = False
        self._probe_at = 0.0

    @classmethod
    def from_env(cls, endpoint: str,
                 clock: Callable[[], float] = time.monotonic
                 ) -> "CircuitBreaker":
        """``PIO_BREAKER_THRESHOLD`` / ``PIO_BREAKER_RESET`` (seconds)."""
        return cls(
            endpoint,
            failure_threshold=int(_env_float("PIO_BREAKER_THRESHOLD", 5)),
            reset_timeout=_env_float("PIO_BREAKER_RESET", 5.0),
            clock=clock)

    # -- state ------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def is_blocking(self) -> bool:
        """True when a call made NOW would be refused (open, probe not
        yet due). Pure read: never consumes the half-open probe slot —
        health checks and predict-time fast-fails use this."""
        with self._lock:
            return self._state == OPEN and \
                self._clock() - self._opened_at < self.reset_timeout

    @property
    def retry_in(self) -> float:
        """Seconds until the next half-open probe is due (0 when not
        open) — the honest ``Retry-After`` for a fast-fail."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self.reset_timeout
                       - (self._clock() - self._opened_at))

    def _transition(self, to: str) -> None:
        """Caller holds the lock."""
        frm, self._state = self._state, to
        if to == OPEN:
            self._opened_at = self._clock()
        self._emit(frm, to)

    def _emit(self, frm: str, to: str) -> None:
        from predictionio_tpu.utils import metrics, tracing

        metrics.CIRCUIT_STATE.set(_STATE_CODE[to], endpoint=self.endpoint)
        metrics.CIRCUIT_TRANSITIONS.inc(endpoint=self.endpoint, to=to)
        # a zero-length span marks the transition on any active trace
        sp, tok = tracing.begin_span(
            f"circuit.transition {frm}->{to}",
            attributes={"endpoint": self.endpoint, "from": frm, "to": to})
        tracing.finish_span(sp, tok, error=(to == OPEN))
        (logger.warning if to == OPEN else logger.info)(
            "circuit breaker %s: %s -> %s", self.endpoint, frm, to)

    # -- call protocol ----------------------------------------------------
    def before_call(self) -> None:
        """Gate one call. Raises :class:`CircuitOpenError` when open;
        when the reset timeout has elapsed, admits exactly one caller
        as the half-open probe."""
        if not enabled():
            return
        # unlocked fast path: reading the state attr is atomic, and a
        # call slipping through in the instant the breaker opens is
        # indistinguishable from one that started a moment earlier
        if self._state == CLOSED:
            return
        with self._lock:
            if self._state == CLOSED:
                return
            if self._state == OPEN:
                waited = self._clock() - self._opened_at
                if waited < self.reset_timeout:
                    raise CircuitOpenError(
                        self.endpoint, self.reset_timeout - waited)
                self._transition(HALF_OPEN)
                self._probe_out = True
                self._probe_at = self._clock()
                return
            # HALF_OPEN: one probe at a time — but a probe whose outcome
            # never lands (a deferred-success find iterator dropped
            # mid-stream records nothing) must not wedge the slot: past
            # reset_timeout it is presumed lost and the slot is reclaimed.
            if self._probe_out and \
                    self._clock() - self._probe_at < self.reset_timeout:
                raise CircuitOpenError(self.endpoint, 0.1)
            self._probe_out = True
            self._probe_at = self._clock()

    def record_success(self) -> None:
        # steady-healthy fast path, no lock: nothing to update when
        # closed with a clean window (unlocked reads are benign — a
        # racing failure's bookkeeping takes the locked path)
        if self._state == CLOSED and self._consecutive == 0 \
                and not self._outcomes:
            return
        with self._lock:
            self._consecutive = 0
            self._push_outcome(True)
            if self._state == OPEN:
                # a STRAGGLER: a call admitted before the trip, landing
                # late, says nothing about the endpoint NOW — closing
                # here would flap fast-fail off mid-blackout, and each
                # flap costs queries their full read deadline until the
                # breaker re-trips. Only the half-open probe closes.
                return
            self._probe_out = False
            if self._state == HALF_OPEN:
                self._transition(CLOSED)
                self._outcomes.clear()
            elif False not in self._outcomes:
                # a failure-free window carries no error-rate signal;
                # dropping it restores the unlocked fast path (which
                # requires an empty window) for steady-healthy traffic
                self._outcomes.clear()

    def record_failure(self, exc: Optional[BaseException] = None) -> None:
        """Count one failed call. Non-transient failures (client bugs)
        never trip the breaker — the endpoint ANSWERED, which for
        availability purposes is a success: a half-open probe that
        comes back 4xx must close the breaker (and always release the
        probe slot), not wedge it half-open forever."""
        if isinstance(exc, CircuitOpenError):
            return  # our own refusal says nothing about the endpoint
        if exc is not None and not is_transient(exc):
            self.record_success()
            return
        with self._lock:
            self._push_outcome(False)
            self._probe_out = False
            if self._state == HALF_OPEN:
                self._transition(OPEN)  # probe failed: timer restarts
                return
            if self._state == OPEN:
                return
            self._consecutive += 1
            n = len(self._outcomes)
            failed = self._outcomes.count(False)
            if self._consecutive >= self.failure_threshold or (
                    n >= self.min_calls and failed / n >= self.error_rate):
                self._transition(OPEN)

    def _push_outcome(self, ok: bool) -> None:
        self._outcomes.append(ok)
        if len(self._outcomes) > self.window:
            del self._outcomes[:len(self._outcomes) - self.window]

    def reset(self) -> None:
        """Back to pristine CLOSED (tests)."""
        with self._lock:
            if self._state != CLOSED:
                self._transition(CLOSED)
            self._consecutive = 0
            self._outcomes.clear()
            self._probe_out = False


# -- per-endpoint breaker registry -----------------------------------------

_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(endpoint: str) -> CircuitBreaker:
    """The process-wide breaker guarding one endpoint (a storage wire
    URL, or a local backend's name). Get-or-create, so every layer
    touching the endpoint shares one availability view."""
    with _breakers_lock:
        br = _breakers.get(endpoint)
        if br is None:
            br = CircuitBreaker.from_env(endpoint)
            _breakers[endpoint] = br
        return br


def reset_breakers() -> None:
    """Reset every breaker IN PLACE — instances stay registered (test
    isolation). Dropping them instead would orphan the references
    layers cache (DAO wrappers, the wire, the predict-read cache): the
    data path would keep feeding the old instance while
    ``breaker_for``/healthz minted and consulted a fresh one, and the
    two views of endpoint health would diverge forever."""
    with _breakers_lock:
        for br in _breakers.values():
            br.reset()


def endpoint_of(dao) -> Optional[str]:
    """The availability-domain name of one event-store DAO (a wire URL
    for resthttp, the backend name locally; None when unknowable)."""
    return getattr(dao, "resilience_endpoint", None) \
        or getattr(dao, "metrics_backend", None)


def storage_ready(dao) -> bool:
    """Shared readiness check for ``GET /healthz``: the DAO's breaker
    is not refusing calls. One definition for all four servers.
    ``dao`` may be the DAO itself or a zero-arg callable resolving it;
    a resolution failure (storage misconfigured or unresolvable at
    poll time) reads as not-ready, never as a 500 from /healthz."""
    try:
        if callable(dao):
            dao = dao()
        ep = endpoint_of(dao)
        return True if ep is None else not breaker_for(ep).is_blocking
    except Exception:
        return False


# -- degradation context ----------------------------------------------------

_degraded: contextvars.ContextVar[Optional[List[str]]] = \
    contextvars.ContextVar("pio_degraded", default=None)


@contextlib.contextmanager
def degraded_scope():
    """Collect degradation marks for one served query. The serving
    layer opens the scope; any storage layer that swallows a failure
    calls :func:`mark_degraded`; the server reads the list afterwards
    and stamps ``degraded: true`` on the response."""
    reasons: List[str] = []
    token = _degraded.set(reasons)
    try:
        yield reasons
    finally:
        _degraded.reset(token)


def mark_degraded(reason: str) -> None:
    """Record that the current query is being served degraded (no-op
    outside a :func:`degraded_scope`)."""
    reasons = _degraded.get()
    if reasons is not None and reason not in reasons:
        reasons.append(reason)


def in_degraded_scope() -> bool:
    """True when a :func:`degraded_scope` is collecting marks. Storage
    layers that can serve PARTIAL results (the fleet router with a
    dead shard) use this to choose between degrade-and-continue on the
    serving path and fail-loud everywhere else (training reads must
    never silently lose a shard's data)."""
    return _degraded.get() is not None


def degrade_reason_for(exc: BaseException) -> str:
    """Canonical degradation label for one storage failure."""
    if isinstance(exc, CircuitOpenError):
        return "circuit_open"
    if isinstance(exc, TimeoutError):
        return "timeout"
    return "storage_error"
