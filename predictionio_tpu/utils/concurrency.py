"""Host-side task parallelism helpers.

The reference evaluates hyper-parameter sets with Scala parallel
collections (``MetricEvaluator.scala:221-230``, ``FastEvalEngine.scala:
176``). The TPU-host analog is a small thread pool: param-set evaluation
is dominated by device dispatches and BLAS/numpy sections that release
the GIL, so threads overlap the host work and keep the device queue fed
without any process fan-out.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, List, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def eval_workers(requested: int, n_items: int) -> int:
    """Worker count for a param-set sweep: the requested value, else a
    modest CPU-based default, never more than the items."""
    if requested and requested > 0:
        w = int(requested)
    else:
        w = min(4, os.cpu_count() or 2)
    return max(1, min(w, n_items))


def parallel_map(fn: Callable[[T], R], items: Iterable[T],
                 workers: int) -> List[R]:
    """Ordered map over items; serial (no pool) when workers <= 1. A
    worker exception propagates to the caller as it would serially."""
    items = list(items)
    if workers <= 1 or len(items) <= 1:
        return [fn(x) for x in items]
    with ThreadPoolExecutor(max_workers=min(workers, len(items))) as pool:
        return list(pool.map(fn, items))
