"""Cross-cutting utilities (tracing, profiling)."""
