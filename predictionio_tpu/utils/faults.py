"""Deterministic fault injection for the storage plane (``PIO_FAULTS``).

Chaos testing that replays exactly: every rule carries its own seed and
match counter, so the SAME spec against the SAME call sequence fires
the SAME faults — a failing chaos run is a reproducible artifact, not
a flake. Hooked into the storage DAO wrapper
(:mod:`predictionio_tpu.data.storage.observed`) and the resthttp wire,
which consult :func:`maybe_fault` before executing each op.

Spec grammar (README "Resilience & health checks")::

    PIO_FAULTS = rule [ ";" rule ... ]
    rule      = key "=" value [ "," key "=" value ... ]

    keys:
      backend     glob over the backend name ("resthttp", "sqlite",
                  "jsonl*", ...); default "*"
      op          glob over the DAO op ("insert_batch", "find", ...);
                  default "*"
      kind        refuse  -> ConnectionRefusedError (request provably
                             never executed: retriable for ANY op)
                  timeout -> TimeoutError (ambiguous: the op may have
                             executed)
                  error   -> server-error analog (HTTP 5xx shape;
                             "status" and "retry_after" refine it)
                  slow    -> sleep "delay" seconds, then proceed
                  torn    -> a mid-write crash: the caller executes a
                             PARTIAL write, then fails ambiguously
      rate        probability per matching call (seeded — replays
                  exactly); mutually exclusive with "every"
      every       fire on every Nth matching call (1 = always)
      times       fire at most K times, then the rule goes inert
      after       skip the first N matching calls
      seed        per-rule RNG seed (default: 1000 + rule index)
      delay       seconds for "slow" (default 0.05)
      status      HTTP-ish status for "error" (default 503)
      retry_after Retry-After hint attached to "error" failures

Example — 10% transient connection refusals on every resthttp write,
plus one torn write on sqlite's 3rd batch insert::

    PIO_FAULTS="backend=resthttp,op=insert*,kind=refuse,rate=0.1,seed=7;\\
backend=sqlite,op=insert_batch,kind=torn,after=2,times=1"
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
from typing import List, Optional

from predictionio_tpu.utils import resilience


class InjectedFault(Exception):
    """Marker base: every injected failure is one of these."""

    injected = True


class InjectedConnectionRefused(InjectedFault, ConnectionRefusedError):
    """The request provably never reached the backend."""

    pio_retry_class = resilience.SAFE


class InjectedTimeout(InjectedFault, TimeoutError):
    """The op may or may not have executed."""

    pio_retry_class = resilience.AMBIGUOUS


class InjectedServerError(InjectedFault, RuntimeError):
    """HTTP-5xx-shaped backend failure."""

    pio_retry_class = resilience.AMBIGUOUS

    def __init__(self, msg: str, status: int = 503,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.status = int(status)
        if retry_after is not None:
            self.pio_retry_after = float(retry_after)


class InjectedTornWrite(InjectedFault, OSError):
    """Raised AFTER the partial write a ``torn`` rule asked for."""

    pio_retry_class = resilience.AMBIGUOUS


class TornWriteDirective:
    """Returned by :func:`maybe_fault` for ``kind=torn``: the caller
    must execute a partial write, then raise :meth:`error`."""

    def __init__(self, rule: "FaultRule"):
        self.rule = rule

    def error(self) -> InjectedTornWrite:
        return InjectedTornWrite(
            f"injected torn write ({self.rule.describe()})")


_KINDS = ("refuse", "timeout", "error", "slow", "torn")


class FaultSpecError(ValueError):
    pass


class FaultRule:
    """One parsed rule with its own deterministic decision stream."""

    def __init__(self, index: int, backend: str = "*", op: str = "*",
                 kind: str = "error", rate: Optional[float] = None,
                 every: Optional[int] = None, times: Optional[int] = None,
                 after: int = 0, seed: Optional[int] = None,
                 delay: float = 0.05, status: int = 503,
                 retry_after: Optional[float] = None):
        import random

        if kind not in _KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r}; known: {_KINDS}")
        if rate is not None and every is not None:
            raise FaultSpecError("rate and every are mutually exclusive")
        if rate is not None and not 0.0 <= rate <= 1.0:
            raise FaultSpecError(
                f"rate must be in [0, 1], got {rate!r}")
        if every is not None:
            every = int(every)
            if every < 1:
                raise FaultSpecError(
                    f"every must be >= 1 (1 = always), got {every!r}")
        if rate is None and every is None:
            every = 1  # unconditional
        self.backend = backend
        self.op = op
        self.kind = kind
        self.rate = rate
        self.every = every
        self.times = times
        self.after = max(0, int(after))
        self.seed = 1000 + index if seed is None else int(seed)
        self.delay = float(delay)
        self.status = int(status)
        self.retry_after = retry_after
        self._rng = random.Random(self.seed)
        self._matched = 0
        self._fired = 0

    @classmethod
    def parse(cls, text: str, index: int) -> "FaultRule":
        kw: dict = {}
        for field in text.split(","):
            field = field.strip()
            if not field:
                continue
            if "=" not in field:
                raise FaultSpecError(
                    f"fault rule field {field!r} is not key=value")
            k, v = (s.strip() for s in field.split("=", 1))
            if k in ("backend", "op", "kind"):
                kw[k] = v
            elif k in ("rate", "delay", "retry_after"):
                kw[k] = float(v)
            elif k in ("every", "times", "after", "seed", "status"):
                kw[k] = int(v)
            else:
                raise FaultSpecError(f"unknown fault rule key {k!r}")
        return cls(index, **kw)

    def describe(self) -> str:
        sel = f"rate={self.rate}" if self.rate is not None \
            else f"every={self.every}"
        return (f"backend={self.backend},op={self.op},kind={self.kind},"
                f"{sel},seed={self.seed}")

    def matches(self, backend: str, op: str) -> bool:
        return fnmatch.fnmatchcase(backend, self.backend) and \
            fnmatch.fnmatchcase(op, self.op)

    def decide(self) -> bool:
        """One deterministic decision for a matching call. The RNG is
        consumed on EVERY matching call (fired or not), so decision N
        is a pure function of (seed, N) and replays exactly."""
        self._matched += 1
        # consume the rng unconditionally to keep the stream aligned
        draw = self._rng.random()
        if self._matched <= self.after:
            return False
        if self.times is not None and self._fired >= self.times:
            return False
        if self.rate is not None:
            fire = draw < self.rate
        else:
            fire = (self._matched - self.after) % self.every == 0
        if fire:
            self._fired += 1
        return fire


class FaultInjector:
    """A parsed ``PIO_FAULTS`` spec; thread-safe, deterministic per
    rule (decision order across threads is the caller's concern —
    chaos suites drive deterministic call sequences)."""

    def __init__(self, rules: List[FaultRule], spec: str = ""):
        self.rules = rules
        self.spec = spec
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultInjector":
        rules = [FaultRule.parse(part, i)
                 for i, part in enumerate(spec.split(";"))
                 if part.strip()]
        return cls(rules, spec)

    def maybe_fault(self, backend: str,
                    op: str) -> Optional[TornWriteDirective]:
        """Consult every rule in order for one storage call. Raises the
        injected failure, sleeps for ``slow``, or returns a
        :class:`TornWriteDirective` the caller must honor."""
        torn: Optional[TornWriteDirective] = None
        slept = 0.0
        for rule in self.rules:
            if not rule.matches(backend, op):
                continue
            with self._lock:
                fire = rule.decide()
            if not fire:
                continue
            _count_fault(backend, op, rule.kind)
            if rule.kind == "slow":
                slept += rule.delay
                continue
            if slept:
                time.sleep(slept)
                slept = 0.0  # spent: the trailing sleep must not repeat it
            if rule.kind == "refuse":
                raise InjectedConnectionRefused(
                    f"injected connection refused ({rule.describe()})")
            if rule.kind == "timeout":
                raise InjectedTimeout(
                    f"injected timeout ({rule.describe()})")
            if rule.kind == "error":
                raise InjectedServerError(
                    f"injected server error ({rule.describe()})",
                    status=rule.status, retry_after=rule.retry_after)
            torn = TornWriteDirective(rule)  # kind == "torn"
        if slept:
            time.sleep(slept)
        return torn


def _count_fault(backend: str, op: str, kind: str) -> None:
    from predictionio_tpu.utils import metrics

    metrics.FAULTS_INJECTED.inc(backend=backend, op=op, kind=kind)


# -- process-wide injector --------------------------------------------------

_injector: Optional[FaultInjector] = None
_pinned = False  # install() overrides the env until clear()
_lock = threading.Lock()


def injector() -> Optional[FaultInjector]:
    """The active injector, tracking ``PIO_FAULTS`` (re-parsed when the
    env value changes, so subprocess servers and test fixtures both
    work); ``None`` when no faults are configured."""
    global _injector
    # lock-free fast path for the (production) no-faults case: one env
    # dict lookup per storage op
    if not _pinned and _injector is None \
            and not os.environ.get("PIO_FAULTS"):
        return None
    spec = os.environ.get("PIO_FAULTS", "").strip()
    with _lock:
        if _pinned:
            return _injector
        if not spec:
            _injector = None
        elif _injector is None or _injector.spec != spec:
            _injector = FaultInjector.parse(spec)
        return _injector


def install(spec: str) -> FaultInjector:
    """Pin an injector regardless of the env (tests). :func:`clear`
    releases it."""
    global _injector, _pinned
    with _lock:
        _injector = FaultInjector.parse(spec)
        _pinned = True
        return _injector


def clear() -> None:
    global _injector, _pinned
    with _lock:
        _injector = None
        _pinned = False


def maybe_fault(backend: str, op: str) -> Optional[TornWriteDirective]:
    """Fast-path entry the storage layers call: no spec, no cost beyond
    one env lookup."""
    inj = injector()
    if inj is None:
        return None
    return inj.maybe_fault(backend, op)
