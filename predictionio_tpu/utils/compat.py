"""Version compatibility shims.

``parse_iso8601`` — one ISO-8601 parsing path for the whole codebase.
Python 3.11+ ``datetime.fromisoformat`` accepts most ISO-8601 variants,
but 3.10 only parses exactly what ``isoformat()`` emits: no ``Z``
suffix, fractional seconds must be exactly 3 or 6 digits, and the UTC
offset needs a colon. Event producers (and the reference's Joda-based
wire format) routinely emit ``...T12:00:00Z`` or ``.1``/``.1234567``
fractions, so every caller that parsed timestamps directly hit
``ValueError`` on 3.10. All ISO parsing routes through here instead.
"""

from __future__ import annotations

import datetime as _dt
import re

# a fraction is only legal after explicit seconds: ISO-8601 fractional
# MINUTES ("12:30.5" = 12:30:30) must be rejected like fromisoformat
# does, not silently mis-read as fractional seconds
_ISO_RE = re.compile(
    r"^(?P<date>\d{4}-\d{2}-\d{2})"
    r"(?:[T ](?P<hm>\d{2}:\d{2})"
    r"(?::(?P<sec>\d{2})(?P<frac>\.\d+)?)?"
    r"(?P<tz>[Zz]|[+-]\d{2}:?\d{2}(?::\d{2})?)?)?$")


def parse_iso8601(s: str) -> _dt.datetime:
    """``datetime.fromisoformat`` accepting ``Z``-suffixed timestamps,
    any fractional-second width (truncated to microseconds), and
    colon-less UTC offsets — identically on every supported Python.

    Raises ``ValueError`` on unparseable input, like ``fromisoformat``.
    """
    try:
        return _dt.datetime.fromisoformat(s)
    except ValueError:
        pass
    m = _ISO_RE.match(s)
    if m is None:
        raise ValueError(f"Invalid isoformat string: {s!r}")
    out = m.group("date")
    if m.group("hm") is not None:
        out += "T" + m.group("hm") + ":" + (m.group("sec") or "00")
        frac = m.group("frac")
        if frac:
            out += "." + (frac[1:] + "000000")[:6]
        tz = m.group("tz")
        if tz:
            if tz in ("Z", "z"):
                tz = "+00:00"
            elif ":" not in tz:
                tz = tz[:3] + ":" + tz[3:]
            out += tz
    return _dt.datetime.fromisoformat(out)
