"""Device-plane flight recorder: per-dispatch telemetry for live serving.

The host plane has been observable since PR 2/4 (metrics + trace trees),
but every DEVICE-side question was unanswerable: how much device time a
dispatch cost, whether it hit the AOT ladder or fell back to jit, how
full the batch was, how long it queued. This module is the bounded,
thread-safe ring those answers live in — the ALX-style per-step
device-time accounting, applied to the serving plane:

- every device dispatch (user top-k, batched users, item similarity,
  the fold-in solve) records one :class:`DispatchRecord`: lane, k/batch
  bucket shape, batch size + fill ratio, store precision, kernel lane
  (fused Pallas vs XLA chain), AOT ladder result (``hit`` /
  ``miss_jit`` / ``jit`` for unladdered programs), queue wait, host
  wall µs and **device µs** — the dispatch-to-``block_until_ready``
  window on the monotonic clock;
- the ring is bounded (``PIO_DEVICE_TELEMETRY_RING``, default 2048):
  a long-lived server holds the last N dispatches, never all of them
  (evictions are counted, not silently dropped);
- surfaces: ``GET /dispatches.json`` on the query server (snapshot +
  per-lane summary), the ``pio_dispatch_device_seconds`` histogram,
  ``device.execute`` child spans in the PR-4 trace tree (Perfetto shows
  device time under each ``device.*`` span), and ``pio top``;
- kill switch ``PIO_DEVICE_TELEMETRY=0``: every record site returns on
  one attribute check before touching a clock or a lock — the same
  killed-lane fast-path discipline as ``PIO_METRICS`` (PR 2), gated by
  the <5% serving-overhead bench/test either way.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "RECORDER",
    "recorder",
    "enabled",
    "set_enabled",
    "record_dispatch",
    "last_record",
    "dispatch_scope",
    "current_dispatch_context",
]


def _env_enabled() -> bool:
    return os.environ.get("PIO_DEVICE_TELEMETRY", "1").strip().lower() \
        not in ("0", "off", "false")


def _env_capacity(default: int = 2048) -> int:
    raw = os.environ.get("PIO_DEVICE_TELEMETRY_RING", "").strip()
    try:
        cap = int(raw) if raw else default
    except ValueError:
        cap = default
    return max(16, cap)


class FlightRecorder:
    """Bounded thread-safe ring of per-dispatch telemetry records.

    Records are plain dicts (JSON-shaped at write time; the scrape path
    never touches device state). ``recorded`` counts every record ever
    taken; ``evicted`` = recorded − retained, so a scraper can tell a
    quiet server from one whose history rolled over.
    """

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self.capacity = _env_capacity() if capacity is None \
            else max(16, int(capacity))
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._recorded = 0
        # the most recent record taken by THIS thread — how a batching
        # dispatcher hands the dispatch record to the result object
        # without changing the users_topk return signature
        self._tls = threading.local()

    # -- write side --------------------------------------------------------

    def record(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._ring.append(rec)
            self._recorded += 1
        self._tls.last = rec
        return rec

    def last(self) -> Optional[Dict[str, Any]]:
        """The most recent record taken on the CALLING thread (None when
        telemetry is off or this thread never dispatched)."""
        return getattr(self._tls, "last", None)

    # -- read side ---------------------------------------------------------

    def snapshot(self, limit: int = 100) -> List[Dict[str, Any]]:
        """The newest ``limit`` records, newest first (0 -> none —
        summaries-only scrapers pass limit=0 to skip the bulk)."""
        limit = int(limit)
        if limit <= 0:
            return []
        with self._lock:
            recent = list(self._ring)[-limit:]
        return recent[::-1]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            retained = len(self._ring)
            recorded = self._recorded
        return {"recorded": recorded, "retained": retained,
                "evicted": recorded - retained,
                "capacity": self.capacity}

    def summary(self) -> Dict[str, Any]:
        """Per-lane aggregates over the retained window: dispatch count,
        device/host-µs percentiles, queue-wait p50, mean batch fill,
        AOT hit/miss counts — the compact view ``pio top`` and the bench
        artifacts embed."""
        with self._lock:
            records = list(self._ring)
        lanes: Dict[str, List[Dict[str, Any]]] = {}
        for r in records:
            lanes.setdefault(r.get("lane", "?"), []).append(r)

        def pct(vals: List[float], q: float) -> Optional[float]:
            if not vals:
                return None
            vals = sorted(vals)
            i = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
            return round(vals[i], 1)

        out: Dict[str, Any] = {}
        for lane, rs in sorted(lanes.items()):
            dev = [r["deviceUs"] for r in rs
                   if r.get("deviceUs") is not None]
            host = [r["hostUs"] for r in rs if r.get("hostUs") is not None]
            waits = [r["queueWaitUs"] for r in rs
                     if r.get("queueWaitUs") is not None]
            fills = [r["fill"] for r in rs if r.get("fill") is not None]
            aot = collections.Counter(r.get("aot", "?") for r in rs)
            out[lane] = {
                "dispatches": len(rs),
                "deviceUsP50": pct(dev, 0.50),
                "deviceUsP99": pct(dev, 0.99),
                "hostUsP50": pct(host, 0.50),
                "hostUsP99": pct(host, 0.99),
                "queueWaitUsP50": pct(waits, 0.50),
                "meanFill": round(sum(fills) / len(fills), 4)
                if fills else None,
                "aot": dict(aot),
            }
        return out

    def report(self, limit: int = 100) -> Dict[str, Any]:
        """The ``GET /dispatches.json`` payload."""
        return {
            "enabled": self.enabled,
            **self.counts(),
            "summary": self.summary(),
            "dispatches": self.snapshot(limit),
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recorded = 0
        self._tls = threading.local()


RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return RECORDER


def enabled() -> bool:
    """THE kill-switch check every dispatch site makes first — one
    attribute read, no lock, no clock (``PIO_DEVICE_TELEMETRY=0``)."""
    return RECORDER.enabled


def set_enabled(flag: bool) -> None:
    RECORDER.enabled = bool(flag)


def last_record() -> Optional[Dict[str, Any]]:
    return RECORDER.last()


# -- dispatch context --------------------------------------------------------

# What the batching dispatcher knows that the device dispatch site does
# not: how long the group queued and how many requests share the
# dispatch. Thread-local (the dispatcher calls the dispatch fn
# synchronously on its own thread), never crosses threads.
_dispatch_ctx = threading.local()


@contextlib.contextmanager
def dispatch_scope(queue_wait_us: Optional[float] = None,
                   group: Optional[int] = None,
                   trace_parent: Any = None):
    """Bind batching context for the device dispatch(es) the block
    issues: queue wait of the oldest grouped query, the group size, and
    a trace parent for the ``device.execute`` span (the dispatcher
    thread has no ambient trace context of its own)."""
    prior = getattr(_dispatch_ctx, "ctx", None)
    _dispatch_ctx.ctx = {"queueWaitUs": queue_wait_us, "group": group,
                         "traceParent": trace_parent}
    try:
        yield
    finally:
        _dispatch_ctx.ctx = prior


def current_dispatch_context() -> Optional[Dict[str, Any]]:
    return getattr(_dispatch_ctx, "ctx", None)


def record_dispatch(*, lane: str, kernel: str, precision: str, aot: str,
                    k_bucket: int, batch: int, bucket: int,
                    host_us: float, device_us: float,
                    started_epoch: Optional[float] = None
                    ) -> Optional[Dict[str, Any]]:
    """Record one device dispatch (caller already paid the timing; this
    is pure bookkeeping). Returns the record dict, or None when the
    recorder is disabled. Also feeds ``pio_dispatch_device_seconds``
    and ``pio_aot_cache_requests_total`` — both behind the PR-2 metrics
    switch independently of this recorder's own kill switch."""
    if not RECORDER.enabled:
        return None
    ctx = current_dispatch_context() or {}
    rec: Dict[str, Any] = {
        "ts": started_epoch if started_epoch is not None else time.time(),
        "lane": lane,
        "kernel": kernel,
        "precision": precision,
        "aot": aot,
        "kBucket": int(k_bucket),
        "batch": int(batch),
        "bucket": int(bucket),
        "fill": round(batch / bucket, 4) if bucket else None,
        "queueWaitUs": None if ctx.get("queueWaitUs") is None
        else round(float(ctx["queueWaitUs"]), 1),
        "hostUs": round(float(host_us), 1),
        "deviceUs": round(float(device_us), 1),
    }
    RECORDER.record(rec)
    from predictionio_tpu.utils import metrics

    metrics.DISPATCH_DEVICE_SECONDS.observe(
        device_us / 1e6, lane=lane, kernel=kernel, precision=precision)
    return rec
