"""Batch prediction subsystem (``pio batchpredict``): bulk offline
scoring through the full DASE serve path in device-shaped, restartable
chunks. See :mod:`predictionio_tpu.batch.predict`."""

from predictionio_tpu.batch.predict import (
    BatchPredictConfig,
    BatchPredictor,
    Manifest,
    chunk_spans,
    input_fingerprint,
    read_queries_jsonl,
    read_results,
    run_batch_predict,
    run_smoke,
    synthesize_queries,
)

__all__ = [
    "BatchPredictConfig",
    "BatchPredictor",
    "Manifest",
    "chunk_spans",
    "input_fingerprint",
    "read_queries_jsonl",
    "read_results",
    "run_batch_predict",
    "run_smoke",
    "synthesize_queries",
]
