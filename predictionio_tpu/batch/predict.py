"""Device-sharded batch prediction — the ``pio batchpredict`` engine.

The deployed REST server answers one query per request; production users
also need to score their *entire* user base offline (nightly top-K for
every user, bulk campaign scoring) — the workload later PredictionIO
releases added ``pio batchpredict`` for. This module composes the
ingredients the repo already has into that workload:

- **Input**: a JSONL query file (one query object per line, the same
  wire format as ``POST /queries.json``) *or* queries synthesized from
  the event store — one per known entity via the materialized
  entity-property aggregation (O(current entities), not O(history)).
- **Serve path**: each query runs the full DASE serve pipeline — typed
  query extraction (``query_from_json``) → ``supplement`` → per-algorithm
  ``batch_predict`` → ``serve`` with the ORIGINAL query — so results are
  identical to looping the deployed server over the same queries, while
  known-user chunks collapse into a handful of batched device dispatches
  (``DeviceTopK.users_topk``: pad to a power-of-two uid bucket, one
  round trip per chunk; ALX's batched-inference shape).
- **Chunking**: queries are split into fixed-shape chunks (power-of-two
  aligned via ``ops.serving.bucket_size`` so the jit caches stay warm
  across chunks) or into ``--query-partitions`` balanced spans
  (``parallel.mesh.shard_spans`` — DrJAX's map-over-shards index math).
  A mesh-sharded model (PAlgorithm ShardedALSModel) serves each chunk
  against its HBM shards through the same program — no host gather.
- **Restartability**: each chunk lands in its own shard file under the
  output directory, fsync'd via atomic rename, and ``manifest.json``
  records chunk → input span → checksum → status. A rerun verifies the
  input fingerprint, skips chunks whose shard checksum still matches,
  and re-scores torn/missing ones — a killed 10M-query job resumes
  instead of restarting.
- **Observability**: per-chunk metrics in the process registry
  (``pio_batchpredict_queries_total``, ``pio_batchpredict_chunk_seconds``,
  ``pio_batchpredict_queries_per_sec``).

Output formats: ``jsonl`` (one ``{"query": ..., "prediction": ...}``
object per line — the reference ``BatchPredict.scala`` shape) or ``npz``
(two aligned string columns, the columnar analog).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import sys
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.controller.algorithms import ordered_batch_results
from predictionio_tpu.core.context import ComputeContext, workflow_context
from predictionio_tpu.parallel.mesh import shard_spans
from predictionio_tpu.utils import metrics
from predictionio_tpu.utils.tracing import span, trace_scope
from predictionio_tpu.workflow.create_server import (
    Deployment,
    build_deployment,
    query_from_json,
    resolve_engine_instance,
    serve_query,
    to_jsonable,
    warm_up,
)

logger = logging.getLogger("pio.batchpredict")

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"
JOURNAL_NAME = MANIFEST_NAME + ".journal"
FORMATS = ("jsonl", "npz")


@dataclasses.dataclass
class BatchPredictConfig:
    """One batch-prediction job (the ``pio batchpredict`` argument set)."""

    output_dir: str
    engine_instance_id: Optional[str] = None
    engine_id: str = "default"
    engine_version: str = "default"
    engine_variant: str = "engine.json"
    # exactly one query source: a JSONL file, or synthesis from the
    # event store (one query per known entity of the given type)
    input_path: Optional[str] = None
    synthesize_app: Optional[str] = None
    synthesize_entity_type: str = "user"
    synthesize_field: str = "user"
    synthesize_base: Mapping[str, Any] = dataclasses.field(
        default_factory=dict)
    synthesize_channel: Optional[str] = None
    # chunking: fixed chunk_size (power-of-two aligned), or an explicit
    # partition count (balanced spans over the query list)
    chunk_size: int = 256
    query_partitions: Optional[int] = None
    format: str = "jsonl"
    batch: str = ""
    warm: bool = True
    # fault injection for crash-resume tests: raise after K chunks scored
    fail_after_chunks: Optional[int] = None


# ---------------------------------------------------------------------------
# Query sources
# ---------------------------------------------------------------------------

def read_queries_jsonl(path: str) -> List[Dict[str, Any]]:
    """One JSON query object per line (blank lines skipped) — the same
    wire format the deployed server's ``POST /queries.json`` accepts."""
    queries: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid JSON: {e}") \
                    from e
            if not isinstance(obj, dict):
                raise ValueError(
                    f"{path}:{lineno}: query must be a JSON object")
            queries.append(obj)
    return queries


def synthesize_queries(app_name: str, entity_type: str = "user",
                       field: str = "user",
                       channel_name: Optional[str] = None,
                       base: Optional[Mapping[str, Any]] = None
                       ) -> List[Dict[str, Any]]:
    """One query per known entity, in sorted entity-id order: the
    "score every user" job without materializing a query file. Served
    from the materialized entity-property aggregation, so enumerating
    10M users is O(current entities), not an event-history replay."""
    from predictionio_tpu.data.store import PEventStore

    props = PEventStore.aggregate_properties(
        app_name=app_name, entity_type=entity_type,
        channel_name=channel_name)
    base = dict(base or {})
    if field in base:
        raise ValueError(
            f"synthesize_base must not set the entity field {field!r}")
    return [{**base, field: eid} for eid in sorted(props)]


# ---------------------------------------------------------------------------
# Manifest + shard files
# ---------------------------------------------------------------------------

def _canonical_query_lines(queries: Sequence[Mapping[str, Any]]) -> List[str]:
    return [json.dumps(q, sort_keys=True, separators=(",", ":"))
            for q in queries]


def input_fingerprint(query_lines: Sequence[str]) -> str:
    """sha256 over the canonical query stream — resume refuses to mix
    shards scored from different inputs."""
    h = hashlib.sha256()
    for line in query_lines:
        h.update(line.encode("utf-8"))
        h.update(b"\n")
    return h.hexdigest()


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    from predictionio_tpu.data.storage.localfs import atomic_write_bytes

    atomic_write_bytes(path, data)


def chunk_spans(n: int, chunk_size: int,
                query_partitions: Optional[int] = None
                ) -> List[Tuple[int, int]]:
    """The chunk plan: ``query_partitions`` balanced spans when given
    (map-over-shards), else fixed ``chunk_size`` chunks. Chunk sizes are
    power-of-two aligned by the serving layer's uid bucketing either
    way, so every chunk after the first reuses a compiled program."""
    if query_partitions is not None:
        return shard_spans(n, query_partitions)
    if chunk_size <= 0:
        raise ValueError(f"chunk_size must be positive, got {chunk_size}")
    from predictionio_tpu.ops.serving import bucket_size

    # align the fixed size to the serving bucket it will dispatch at
    c = bucket_size(min(chunk_size, max(n, 1)), lo=8)
    return [(i, min(i + c, n)) for i in range(0, n, c)]


class Manifest:
    """``manifest.json`` — the restart contract: chunk id → input span →
    shard file → checksum → status, plus the input fingerprint the
    shards were scored from.

    Completion is recorded per chunk in an append-only JOURNAL
    (``manifest.json.journal``: one ``{"id", "sha256"}`` line per done
    chunk) and compacted into ``manifest.json`` once at the end of a
    run — rewriting the full manifest after every chunk would be
    O(chunks²) on a 10M-query job. ``load`` replays the journal, so a
    killed run's completed chunks are visible to the resume."""

    def __init__(self, data: Dict[str, Any]):
        self.data = data

    @classmethod
    def fresh(cls, instance_id: str, fmt: str, source: str,
              fingerprint: str, count: int,
              spans: Sequence[Tuple[int, int]]) -> "Manifest":
        ext = "jsonl" if fmt == "jsonl" else "npz"
        return cls({
            "formatVersion": MANIFEST_VERSION,
            "engineInstanceId": instance_id,
            "format": fmt,
            "input": {"source": source, "sha256": fingerprint,
                      "count": count},
            "chunks": [
                {"id": i, "start": start, "count": stop - start,
                 "file": f"part-{i:05d}.{ext}", "status": "pending",
                 "sha256": None}
                for i, (start, stop) in enumerate(spans)
            ],
        })

    @classmethod
    def load(cls, path: str) -> Optional["Manifest"]:
        if not os.path.exists(path):
            return None
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            # a torn manifest (crash mid-replace cannot happen with the
            # atomic write, but a hand-edited one can) restarts the job
            logger.warning("unreadable manifest at %s; starting fresh",
                           path)
            return None
        if not isinstance(data, dict) \
                or data.get("formatVersion") != MANIFEST_VERSION:
            return None
        manifest = cls(data)
        manifest._apply_journal(path + ".journal")
        return manifest

    def _apply_journal(self, journal_path: str) -> None:
        """Fold journal completion lines into the chunk table. A torn
        trailing line (killed mid-append) is ignored — that chunk simply
        re-scores."""
        if not os.path.exists(journal_path):
            return
        by_id = {c["id"]: c for c in self.data.get("chunks", ())}
        with open(journal_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                    chunk = by_id.get(entry["id"])
                    sha = entry["sha256"]
                except (json.JSONDecodeError, TypeError, KeyError):
                    continue
                if chunk is not None and isinstance(sha, str):
                    chunk["status"] = "done"
                    chunk["sha256"] = sha

    def save(self, path: str) -> None:
        _atomic_write(path, json.dumps(
            self.data, sort_keys=True, indent=1).encode("utf-8"))

    def matches(self, instance_id: str, fmt: str, fingerprint: str,
                count: int) -> bool:
        inp = self.data.get("input") or {}
        return (self.data.get("engineInstanceId") == instance_id
                and self.data.get("format") == fmt
                and inp.get("sha256") == fingerprint
                and inp.get("count") == count)

    @property
    def chunks(self) -> List[Dict[str, Any]]:
        return self.data["chunks"]


# ---------------------------------------------------------------------------
# The predictor
# ---------------------------------------------------------------------------

class BatchPredictor:
    """Score a query stream through a loaded engine instance in
    device-shaped chunks, writing restartable per-chunk shards."""

    def __init__(self, config: BatchPredictConfig,
                 engine: Optional[Any] = None,
                 ctx: Optional[ComputeContext] = None):
        if config.format not in FORMATS:
            raise ValueError(
                f"unknown output format {config.format!r}; "
                f"expected one of {FORMATS}")
        sources = (config.input_path is not None,
                   config.synthesize_app is not None)
        if sum(sources) != 1:
            raise ValueError(
                "exactly one query source required: --input or "
                "--synthesize-app")
        self.config = config
        self._engine_override = engine
        self.ctx = ctx or workflow_context(mode="serving",
                                           batch=config.batch)
        self._deployment: Optional[Deployment] = None

    # -- loading -----------------------------------------------------------

    def load(self) -> Deployment:
        """Resolve + load the engine instance (shared with deploy:
        ``build_deployment``), then AOT-warm the predict path so no
        chunk pays a serve-time compile."""
        if self._deployment is None:
            cfg = self.config
            instance = resolve_engine_instance(
                cfg.engine_instance_id, cfg.engine_id,
                cfg.engine_version, cfg.engine_variant)
            dep = build_deployment(instance, self.ctx,
                                   engine=self._engine_override,
                                   batch=cfg.batch)
            if cfg.warm:
                warm_up(dep)
            self._deployment = dep
            logger.info("Engine instance %s loaded for batch prediction",
                        instance.id)
        return self._deployment

    def read_queries(self) -> List[Dict[str, Any]]:
        cfg = self.config
        if cfg.input_path is not None:
            return read_queries_jsonl(cfg.input_path)
        return synthesize_queries(
            cfg.synthesize_app, entity_type=cfg.synthesize_entity_type,
            field=cfg.synthesize_field,
            channel_name=cfg.synthesize_channel,
            base=cfg.synthesize_base)

    # -- scoring -----------------------------------------------------------

    def score_chunk(self, dep: Deployment,
                    query_dicts: Sequence[Mapping[str, Any]]) -> List[Any]:
        """One chunk through the full DASE serve path, batched: typed
        extraction → supplement → per-algorithm ``batch_predict`` (ONE
        device job per algorithm for device-served models) → serve with
        the original query. Result order == input order."""
        query_cls = dep.algorithms[0].query_class
        typed = [query_from_json(q, query_cls) for q in query_dicts]
        indexed = list(enumerate(typed))
        supplemented = [(qx, dep.serving.supplement_base(q))
                        for qx, q in indexed]
        per_algo: List[List[Any]] = []
        for algo, model in zip(dep.algorithms, dep.models):
            results = algo.batch_predict_base(self.ctx, model, supplemented)
            per_algo.append(ordered_batch_results(
                supplemented, results, who=type(algo).__name__))
        return [
            dep.serving.serve_base(q, [col[qx] for col in per_algo])
            for qx, q in indexed
        ]

    def serve_one(self, query_dict: Mapping[str, Any]) -> Any:
        """The looped single-query reference path (what the deployed
        server does per request) — used by tests and the bench to prove
        chunked scoring is equivalent and faster."""
        dep = self.load()
        query = query_from_json(dict(query_dict),
                                dep.algorithms[0].query_class)
        return serve_query(dep, query)

    @staticmethod
    def _render_records(query_lines: Sequence[str],
                        predictions: Sequence[Any]) -> List[str]:
        """Wire records: ``{"prediction": ..., "query": ...}`` JSON per
        query, canonical key order — identical bytes from identical
        predictions, so shard checksums are meaningful."""
        out = []
        for line, p in zip(query_lines, predictions):
            rendered = json.dumps(to_jsonable(p), sort_keys=True,
                                  separators=(",", ":"))
            out.append('{"prediction":' + rendered
                       + ',"query":' + line + "}")
        return out

    def _write_shard(self, path: str, records: List[str],
                     start: int) -> str:
        """Write one shard atomically; returns the sha256 of the bytes
        written (hashed in memory — re-reading the file we just wrote
        would double the job's output IO for nothing)."""
        if self.config.format == "jsonl":
            data = ("\n".join(records) + "\n").encode("utf-8")
        else:
            import io

            buf = io.BytesIO()
            # an aligned record column + the input span — the columnar
            # shard shape (each record string is a jsonl line's content)
            np.savez_compressed(
                buf, format_version=np.int64(MANIFEST_VERSION),
                start=np.int64(start), count=np.int64(len(records)),
                records=np.asarray(records, dtype=np.str_))
            data = buf.getvalue()
        _atomic_write(path, data)
        return hashlib.sha256(data).hexdigest()


    # -- the job -----------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        """Score everything, resuming from a prior manifest when the
        input/instance/format still match. Returns the run summary. The
        whole run is one trace root with a span per scored chunk, so a
        stalled bulk job decomposes in Perfetto just like a slow query
        (``--trace-dir`` / ``$PIO_TRACE_DIR`` exports it)."""
        with trace_scope("pio.batchpredict",
                         attributes={"output": self.config.output_dir},
                         slow_exempt=True):
            return self._run()

    def _run(self) -> Dict[str, Any]:
        cfg = self.config
        dep = self.load()
        queries = self.read_queries()
        if not queries:
            # a bulk job over nothing is a misconfiguration (wrong app,
            # entity type never $set, empty file), not a success
            raise ValueError(
                "no queries to score (empty input / no known entities "
                f"of type {cfg.synthesize_entity_type!r})"
                if cfg.input_path is None else
                f"no queries to score ({cfg.input_path} is empty)")
        query_lines = _canonical_query_lines(queries)
        fingerprint = input_fingerprint(query_lines)
        source = cfg.input_path or (
            f"synthesized:{cfg.synthesize_app}/{cfg.synthesize_entity_type}")

        os.makedirs(cfg.output_dir, exist_ok=True)
        manifest_path = os.path.join(cfg.output_dir, MANIFEST_NAME)
        manifest = Manifest.load(manifest_path)
        if manifest is not None and not manifest.matches(
                dep.instance.id, cfg.format, fingerprint, len(queries)):
            raise ValueError(
                f"{cfg.output_dir} holds results for a different job "
                "(engine instance, input fingerprint or format differ); "
                "use a fresh --output directory")
        journal_path = os.path.join(cfg.output_dir, JOURNAL_NAME)
        if manifest is None:
            spans = chunk_spans(len(queries), cfg.chunk_size,
                                cfg.query_partitions)
            manifest = Manifest.fresh(dep.instance.id, cfg.format, source,
                                      fingerprint, len(queries), spans)
            # a stale journal (manifest removed by hand) must not mark
            # fresh chunks done
            if os.path.exists(journal_path):
                os.unlink(journal_path)
            manifest.save(manifest_path)
        # resume NEVER rechunks: the manifest's spans are the layout the
        # completed shards were scored at

        scored = skipped = scored_queries = 0
        t_run = time.perf_counter()
        scoring_sec = 0.0
        journal = open(journal_path, "a", encoding="utf-8")
        try:
            for chunk in manifest.chunks:
                path = os.path.join(cfg.output_dir, chunk["file"])
                if chunk["status"] == "done" and chunk["sha256"] \
                        and os.path.exists(path) \
                        and _file_sha256(path) == chunk["sha256"]:
                    skipped += 1
                    metrics.BATCHPREDICT_QUERIES.inc(chunk["count"],
                                                     status="skipped")
                    continue
                # pending, torn or missing -> (re)score the whole span
                if cfg.fail_after_chunks is not None \
                        and scored >= cfg.fail_after_chunks:
                    raise RuntimeError(
                        f"fault injection: stopping after {scored} chunks")
                start = chunk["start"]
                stop = start + chunk["count"]
                t0 = time.perf_counter()
                with span("batchpredict.chunk",
                          attributes={"chunk": chunk["id"],
                                      "queries": stop - start}):
                    predictions = self.score_chunk(dep,
                                                   queries[start:stop])
                    records = self._render_records(
                        query_lines[start:stop], predictions)
                    chunk["sha256"] = self._write_shard(path, records,
                                                        start)
                chunk["status"] = "done"
                # O(1) completion record; compacted into manifest.json
                # once at the end (a full rewrite per chunk is O(n^2))
                journal.write(json.dumps(
                    {"id": chunk["id"], "sha256": chunk["sha256"]},
                    separators=(",", ":")) + "\n")
                journal.flush()
                os.fsync(journal.fileno())
                took = time.perf_counter() - t0
                scoring_sec += took
                scored += 1
                scored_queries += stop - start
                metrics.BATCHPREDICT_QUERIES.inc(stop - start,
                                                 status="scored")
                metrics.BATCHPREDICT_CHUNK_LATENCY.observe(took)
                logger.info("chunk %d: %d queries in %.3fs",
                            chunk["id"], stop - start, took)
        finally:
            journal.close()
        manifest.save(manifest_path)  # compact: every chunk now final
        os.unlink(journal_path)

        total_queries = len(queries)
        qps = scored_queries / scoring_sec if scoring_sec > 0 else 0.0
        if scored:
            metrics.BATCHPREDICT_QPS.set(round(qps, 1))
        return {
            "outputDir": cfg.output_dir,
            "engineInstanceId": dep.instance.id,
            "format": cfg.format,
            "queries": total_queries,
            "chunks": len(manifest.chunks),
            "chunksScored": scored,
            "chunksSkipped": skipped,
            "wallSec": round(time.perf_counter() - t_run, 3),
            "scoringSec": round(scoring_sec, 3),
            "queriesPerSec": round(qps, 1),
        }


def run_batch_predict(config: BatchPredictConfig,
                      engine: Optional[Any] = None,
                      ctx: Optional[ComputeContext] = None
                      ) -> Dict[str, Any]:
    """One-call entry: load, score, return the summary."""
    return BatchPredictor(config, engine=engine, ctx=ctx).run()


# ---------------------------------------------------------------------------
# Reading results back (tests, downstream consumers)
# ---------------------------------------------------------------------------

def read_results(output_dir: str) -> List[Dict[str, Any]]:
    """All predictions of a completed run, in input-query order."""
    manifest = Manifest.load(os.path.join(output_dir, MANIFEST_NAME))
    if manifest is None:
        raise ValueError(f"no readable manifest under {output_dir}")
    out: List[Dict[str, Any]] = []
    for chunk in manifest.chunks:
        if chunk["status"] != "done":
            raise ValueError(
                f"chunk {chunk['id']} is {chunk['status']}; the run has "
                "not completed")
        path = os.path.join(output_dir, chunk["file"])
        if manifest.data["format"] == "jsonl":
            with open(path, "r", encoding="utf-8") as f:
                lines = [ln for ln in f.read().splitlines() if ln]
        else:
            z = np.load(path, allow_pickle=False)
            lines = z["records"].tolist()
        if len(lines) != chunk["count"]:
            raise ValueError(
                f"shard {chunk['file']} holds {len(lines)} records, "
                f"manifest says {chunk['count']}")
        out.extend(json.loads(ln) for ln in lines)
    return out


# ---------------------------------------------------------------------------
# Smoke entry (`pio batchpredict --smoke`)
# ---------------------------------------------------------------------------

def run_smoke() -> int:
    """Self-contained CPU smoke: seed a tiny rating store in memory,
    train the recommendation template, batch-predict synthesized
    queries, crash after one chunk, resume, and verify (a) completed
    chunks were not re-scored and (b) the output equals both a clean
    single-pass run and the looped single-query serve path. The cheap
    end-to-end wiring check CI runs on every change."""
    import shutil
    import tempfile

    import datetime as _dt

    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.data import storage
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage import StorageConfig
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.ops.als import ALSParams
    from predictionio_tpu.templates.recommendation import (
        DataSourceParams,
        engine_factory,
    )
    from predictionio_tpu.workflow import run_train
    from predictionio_tpu.workflow.create_workflow import (
        WorkflowConfig,
        new_engine_instance,
    )

    factory_path = "predictionio_tpu.templates.recommendation:engine_factory"
    tmp = tempfile.mkdtemp(prefix="pio_bp_smoke_")
    storage.reset(StorageConfig(
        sources={"SMOKE": {"type": "memory"}},
        repositories={"METADATA": "SMOKE", "EVENTDATA": "SMOKE",
                      "MODELDATA": "SMOKE"}))
    try:
        aid = storage.get_metadata_apps().insert(App(0, "bpsmoke"))
        le = storage.get_levents()
        le.init(aid)
        rng = np.random.default_rng(0)
        t0 = _dt.datetime(2021, 1, 1, tzinfo=_dt.timezone.utc)
        le.insert_batch(
            # $set entities make the users known to the materialized
            # aggregation (what query synthesis enumerates) ...
            [Event(event="$set", entity_type="user", entity_id=f"u{u}",
                   properties={"active": True}, event_time=t0)
             for u in range(24)]
            # ... and rate events feed the ALS training read
            + [Event(event="rate", entity_type="user", entity_id=f"u{u}",
                     target_entity_type="item",
                     target_entity_id=f"i{rng.integers(0, 12)}",
                     properties={"rating": float(rng.integers(1, 6))},
                     event_time=t0)
               for u in range(24) for _ in range(6)], aid)
        params = EngineParams(
            data_source_params=("", DataSourceParams(app_name="bpsmoke")),
            algorithm_params_list=[
                ("als", ALSParams(rank=4, num_iterations=2, seed=0))])
        instance = new_engine_instance(
            WorkflowConfig(engine_factory=factory_path), params)
        iid = run_train(engine_factory(), params, instance,
                        ctx=ComputeContext())
        assert iid is not None

        def cfg(out, **kw):
            return BatchPredictConfig(
                output_dir=out, engine_instance_id=iid,
                synthesize_app="bpsmoke",
                synthesize_base={"num": 3}, chunk_size=8, **kw)

        clean_dir = os.path.join(tmp, "clean")
        resumed_dir = os.path.join(tmp, "resumed")
        clean = run_batch_predict(cfg(clean_dir))
        try:
            run_batch_predict(cfg(resumed_dir, fail_after_chunks=1))
        except RuntimeError:
            pass  # the injected crash
        else:
            raise AssertionError("fault injection did not fire")
        partial = Manifest.load(os.path.join(resumed_dir, MANIFEST_NAME))
        done_before = {c["id"]: c["sha256"] for c in partial.chunks
                       if c["status"] == "done"}
        assert done_before, "no chunk completed before the injected crash"
        summary = run_batch_predict(cfg(resumed_dir))
        assert summary["chunksSkipped"] == len(done_before), summary
        after = Manifest.load(os.path.join(resumed_dir, MANIFEST_NAME))
        for c in after.chunks:
            if c["id"] in done_before:
                assert c["sha256"] == done_before[c["id"]], \
                    f"chunk {c['id']} was re-scored on resume"
        resumed = read_results(resumed_dir)
        assert resumed == read_results(clean_dir), \
            "resumed output differs from the clean single-pass run"

        # looped single-query equivalence on a sample
        bp = BatchPredictor(cfg(os.path.join(tmp, "probe")))
        for rec in resumed[:5]:
            single = to_jsonable(bp.serve_one(rec["query"]))
            assert single == rec["prediction"], \
                f"batch != single for {rec['query']}"
        print(f"[INFO] batchpredict smoke OK: {clean['queries']} queries, "
              f"{clean['chunks']} chunks, resume verified "
              f"({summary['chunksSkipped']} skipped / "
              f"{summary['chunksScored']} re-scored), "
              f"single-query parity verified.")
        return 0
    except AssertionError as e:
        print(f"[ERROR] batchpredict smoke failed: {e}", file=sys.stderr)
        return 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        storage.reset()
