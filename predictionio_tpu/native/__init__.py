"""Native (C++) host-runtime components.

The reference delegates its host-side heavy lifting to JVM dependencies
(Spark data movement, HBase scans); here the equivalent hot host paths are
small C++ libraries loaded via ctypes, with the Python implementation as
both the fallback and the behavioral oracle:

- ``jsonl_codec``: bulk event import/export codec (data/loader plane;
  replaces ``tools/.../imprt/FileToEvents.scala:41-103``'s Spark job).

Build: compiled on demand with g++ into ``_build/`` next to this file
(no pybind11 — plain C ABI). ``PIO_NATIVE_DISABLE=1`` forces the pure
Python paths; build failures degrade silently to Python.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger("pio.native")

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")

_lock = threading.Lock()
_cache: dict = {}


def _build(name: str) -> Optional[str]:
    """Compile src/<name>.cpp -> _build/lib<name>.so if stale; None on
    failure (no toolchain, read-only install, ...)."""
    src = os.path.join(_SRC_DIR, f"{name}.cpp")
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    if not os.path.exists(src):
        return None
    try:
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
               "-o", out, src]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            logger.warning("native build of %s failed:\n%s", name,
                           proc.stderr[-2000:])
            return None
        return out
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build of %s failed: %s", name, e)
        return None


def load(name: str) -> Optional[ctypes.CDLL]:
    """Load (building if needed) lib<name>; None if unavailable."""
    if os.environ.get("PIO_NATIVE_DISABLE") == "1":
        return None
    with _lock:
        if name in _cache:
            return _cache[name]
        lib = None
        path = _build(name)
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError as e:
                logger.warning("failed to load %s: %s", path, e)
        _cache[name] = lib
        return lib


def available(name: str) -> bool:
    return load(name) is not None
