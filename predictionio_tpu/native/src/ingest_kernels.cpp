// Native ingest kernels — the host-side pad/bucketize hot path.
//
// Role: the vectorized core of the streaming ingest pipeline
// (data/columnar.py + ops/als.py). Two kernels:
//
// - pio_merge_runs_i64: stable k-way merge of per-block sorted key runs
//   into one global permutation — replaces the O(N log N) full argsort
//   of the monolithic dedup pass with an O(N log k) merge whose inputs
//   were sorted block-by-block WHILE decode of later blocks was still
//   running. The permutation is bit-identical to
//   np.argsort(keys, kind="stable") over the concatenated runs.
//
// - pio_bucket_fill: one pass over the deduped (row-sorted) triples
//   scattering every entry straight into its bucket's padded
//   cols/weights/mask tables — replaces the per-bucket boolean mask +
//   fancy-index scatter (one full pass over all N entries PER bucket).
//   Pure data movement, so the filled tables are byte-identical to the
//   numpy path.
//
// Both release the GIL for their whole run (plain ctypes calls), so the
// consumer thread can merge/fill while producer threads decode.
//
// C ABI only; loaded via ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Merge two sorted index runs [a_begin, a_end) and [b_begin, b_end)
// (indices into `keys`) into `out`, stable: ties prefer the run whose
// indices are smaller (runs are handed over in ascending index order).
void merge2(const int64_t* keys, const int64_t* a, int64_t na,
            const int64_t* b, int64_t nb, int64_t* out) {
  int64_t i = 0, j = 0, k = 0;
  while (i < na && j < nb) {
    // a's indices all precede b's, so <= keeps stability
    if (keys[a[i]] <= keys[b[j]]) out[k++] = a[i++];
    else out[k++] = b[j++];
  }
  if (i < na) std::memcpy(out + k, a + i, sizeof(int64_t) * (na - i));
  if (j < nb) std::memcpy(out + k, b + j, sizeof(int64_t) * (nb - j));
}

}  // namespace

extern "C" {

// Stable merge of n_runs sorted runs laid out contiguously in `keys`
// (run r spans [offsets[r], offsets[r+1]) and is already sorted
// ascending). Writes the global permutation into `perm` (int64 [n]):
// keys[perm] is ascending and ties keep ascending index order — exactly
// np.argsort(keys, kind="stable"). Balanced pairwise merge: log2(k)
// passes over N.
void pio_merge_runs_i64(const int64_t* keys, const int64_t* offsets,
                        int32_t n_runs, int64_t n, int64_t* perm) {
  if (n <= 0) return;
  if (n_runs <= 1) {
    for (int64_t i = 0; i < n; ++i) perm[i] = i;
    return;
  }
  // seed: each run's identity indices
  std::vector<int64_t> buf_a(n), buf_b(n);
  for (int64_t i = 0; i < n; ++i) buf_a[i] = i;
  // current run boundaries (ascending, runs contiguous in buf)
  std::vector<int64_t> bounds(offsets, offsets + n_runs + 1);
  int64_t* src = buf_a.data();
  int64_t* dst = buf_b.data();
  while (bounds.size() > 2) {
    std::vector<int64_t> next_bounds;
    next_bounds.push_back(0);
    size_t r = 0;
    while (r + 2 < bounds.size()) {
      const int64_t lo = bounds[r], mid = bounds[r + 1], hi = bounds[r + 2];
      merge2(keys, src + lo, mid - lo, src + mid, hi - mid, dst + lo);
      next_bounds.push_back(hi);
      r += 2;
    }
    if (r + 2 == bounds.size()) {  // odd run out: copy through
      const int64_t lo = bounds[r], hi = bounds[r + 1];
      std::memcpy(dst + lo, src + lo, sizeof(int64_t) * (hi - lo));
      next_bounds.push_back(hi);
    }
    std::swap(src, dst);
    bounds.swap(next_bounds);
  }
  std::memcpy(perm, src, sizeof(int64_t) * n);
}

// One-pass scatter of deduped triples into per-bucket padded tables.
// Inputs (all length n, sorted by row — the dedup contract):
//   rows/cols int64, vals float32, pos int64 (position within row).
// Per-row assignment (length n_rows): b_of_row int32 (bucket index),
// rank int64 (row's table row within its bucket; only valid where the
// row has entries). Per-bucket (length n_buckets): L int64 (padded row
// length), and table base pointers cols_out (int32), w_out/m_out
// (float32) — each bucket's table is its own C-contiguous [Bp, L[b]]
// array, zero-initialized by the caller.
void pio_bucket_fill(int64_t n, const int64_t* rows, const int64_t* cols,
                     const float* vals, const int64_t* pos,
                     const int32_t* b_of_row, const int64_t* rank,
                     int32_t n_buckets, const int64_t* L,
                     int32_t** cols_out, float** w_out, float** m_out) {
  (void)n_buckets;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t r = rows[i];
    const int32_t b = b_of_row[r];
    const int64_t at = rank[r] * L[b] + pos[i];
    cols_out[b][at] = static_cast<int32_t>(cols[i]);
    w_out[b][at] = vals[i];
    m_out[b][at] = 1.0f;
  }
}

// Sequential per-key segment boundaries over SORTED keys: writes the
// index of each segment start into `starts` and returns the unique
// count. Identical grouping to
// np.flatnonzero(np.r_[True, k[1:] != k[:-1]]).
int64_t pio_segment_starts_i64(const int64_t* keys, int64_t n,
                               int64_t* starts) {
  if (n <= 0) return 0;
  int64_t m = 0;
  starts[m++] = 0;
  for (int64_t i = 1; i < n; ++i) {
    if (keys[i] != keys[i - 1]) starts[m++] = i;
  }
  return m;
}

}  // extern "C"
