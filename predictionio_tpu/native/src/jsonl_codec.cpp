// Native JSON-lines event codec — the host-side data-loader hot path.
//
// Role: the bulk-import / export data plane the reference delegates to
// Spark jobs (tools/.../imprt/FileToEvents.scala:41-103). One pass over
// the file buffer tokenizes each event line, decodes the string fields
// (escape handling included), captures raw JSON slices for
// properties/tags, parses ISO-8601 timestamps to epoch seconds, and
// pre-computes validation facts (empty-properties, reserved property
// keys). Anything the fast path cannot express 1:1 with the Python
// semantics is flagged `fallback` and re-parsed by the Python oracle, so
// the codec can never change behavior — only speed.
//
// C ABI only; loaded via ctypes (no pybind11 in this environment).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumCols = 12;
// Column ids (keep in sync with native/codec.py):
// 0=event 1=entityType 2=entityId 3=targetEntityType 4=targetEntityId
// 5=properties(raw json) 6=tags(raw json) 7=prId 8=eventId
// 9=eventTime(raw) 10=creationTime(raw) 11=badPropertyKey
enum Flag : uint8_t {
  kFallback = 1,       // python must re-parse this line
  kPropsEmpty = 2,     // properties absent/null/{} ($unset validation)
  kBadPropKey = 4,     // a top-level property key has a reserved prefix
};

struct Col {
  std::string data;               // concatenated utf-8
  std::vector<int64_t> offsets;   // row i -> [offsets[i], offsets[i+1])
  std::vector<uint8_t> present;
};

struct Result {
  Col cols[kNumCols];
  std::vector<double> event_time;     // epoch seconds; NaN = absent/unparsed
  std::vector<double> creation_time;
  std::vector<uint8_t> flags;
  std::vector<int64_t> line_start, line_end, lineno;
  int64_t n = 0;

  void begin_row(int64_t ls, int64_t le, int64_t ln) {
    for (auto& c : cols) {
      c.offsets.push_back(static_cast<int64_t>(c.data.size()));
      c.present.push_back(0);
    }
    event_time.push_back(NAN);
    creation_time.push_back(NAN);
    flags.push_back(0);
    line_start.push_back(ls);
    line_end.push_back(le);
    lineno.push_back(ln);
    ++n;
  }
  // set col value for the CURRENT row (duplicate keys: last wins)
  void set(int col, const char* s, size_t len) {
    Col& c = cols[col];
    c.data.resize(static_cast<size_t>(c.offsets.back()));
    c.data.append(s, len);
    c.present.back() = 1;
  }
  void clear_col(int col) {
    Col& c = cols[col];
    c.data.resize(static_cast<size_t>(c.offsets.back()));
    c.present.back() = 0;
  }
  void finish() {
    for (auto& c : cols) c.offsets.push_back(static_cast<int64_t>(c.data.size()));
  }
};

// Hinnant's days-from-civil (public-domain calendrical algorithm).
int64_t days_from_civil(int64_t y, unsigned m, unsigned d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + static_cast<int64_t>(doe) - 719468;
}

bool parse_uint(const char*& p, const char* end, int digits, int64_t* out) {
  int64_t v = 0;
  for (int i = 0; i < digits; ++i) {
    if (p >= end || *p < '0' || *p > '9') return false;
    v = v * 10 + (*p - '0');
    ++p;
  }
  *out = v;
  return true;
}

// ISO-8601 (datetime.fromisoformat-compatible subset) -> epoch seconds.
// Accepts YYYY-MM-DD[{T| }HH:MM[:SS[.1-6frac]]][±HH:MM]; naive = UTC
// (matching Event.__post_init__'s tz default). Deliberately STRICTER than
// python: anything this rejects falls back to the python parser, so the
// only correctness requirement is that what it accepts, python computes
// identically (callers pre-convert the 'Z' suffix to +00:00).
bool iso_to_epoch(const char* s, size_t len, double* out) {
  const char* p = s;
  const char* end = s + len;
  int64_t Y, M, D, h = 0, mi = 0, sec = 0;
  double frac = 0.0;
  if (!parse_uint(p, end, 4, &Y) || p >= end || *p != '-') return false;
  ++p;
  if (!parse_uint(p, end, 2, &M) || p >= end || *p != '-') return false;
  ++p;
  if (!parse_uint(p, end, 2, &D)) return false;
  if (M < 1 || M > 12 || D < 1) return false;
  static const int kMdays[] = {31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31};
  int dmax = kMdays[M - 1];
  if (M == 2 && (Y % 4 == 0 && (Y % 100 != 0 || Y % 400 == 0))) dmax = 29;
  if (D > dmax) return false;
  int64_t tz_off = 0;
  if (p < end) {
    if (*p != 'T' && *p != ' ') return false;
    ++p;
    if (!parse_uint(p, end, 2, &h) || p >= end || *p != ':') return false;
    ++p;
    if (!parse_uint(p, end, 2, &mi)) return false;
    if (p < end && *p == ':') {
      ++p;
      if (!parse_uint(p, end, 2, &sec)) return false;
      if (p < end && *p == '.') {
        ++p;
        double scale = 0.1;
        int nd = 0;
        while (p < end && *p >= '0' && *p <= '9') {
          frac += (*p - '0') * scale;
          scale *= 0.1;
          ++p;
          ++nd;
        }
        if (nd < 1 || nd > 6) return false;
      }
    }
    if (p < end) {
      if (*p == '+' || *p == '-') {
        int sign = (*p == '-') ? -1 : 1;
        ++p;
        int64_t oh, om;
        if (!parse_uint(p, end, 2, &oh)) return false;
        if (p >= end || *p != ':') return false;
        ++p;
        if (!parse_uint(p, end, 2, &om)) return false;
        if (oh > 23 || om > 59) return false;
        tz_off = sign * (oh * 3600 + om * 60);
      }
    }
    if (p != end) return false;
    if (h > 23 || mi > 59 || sec > 59) return false;
  }
  const int64_t days = days_from_civil(Y, static_cast<unsigned>(M),
                                       static_cast<unsigned>(D));
  *out = static_cast<double>(days * 86400 + h * 3600 + mi * 60 + sec - tz_off)
         + frac;
  return true;
}

struct Parser {
  const char* p;
  const char* end;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n'))
      ++p;
  }
  bool lit(const char* s) {
    size_t l = std::strlen(s);
    if (static_cast<size_t>(end - p) < l || std::memcmp(p, s, l) != 0)
      return false;
    p += l;
    return true;
  }

  // Decode a JSON string (incl. \uXXXX with surrogate pairs) to UTF-8.
  bool string(std::string& out) {
    out.clear();
    if (p >= end || *p != '"') return false;
    ++p;
    while (p < end) {
      unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"') {
        ++p;
        return true;
      }
      if (c == '\\') {
        ++p;
        if (p >= end) return false;
        char e = *p++;
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            uint32_t cp;
            if (!hex4(&cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              if (p + 1 < end && p[0] == '\\' && p[1] == 'u') {
                p += 2;
                uint32_t lo;
                if (!hex4(&lo)) return false;
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                } else {
                  return false;  // invalid pair: python json would error
                }
              } else {
                // lone surrogate: json.loads ACCEPTS it; we can't encode it
                // as valid UTF-8 — punt to the python path
                return false;
              }
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
              return false;  // lone low surrogate: punt
            }
            append_utf8(out, cp);
            break;
          }
          default: return false;
        }
      } else if (c < 0x20) {
        return false;  // control chars must be escaped
      } else {
        out += static_cast<char>(c);
        ++p;
      }
    }
    return false;
  }

  bool hex4(uint32_t* out) {
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      if (p >= end) return false;
      char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else return false;
    }
    *out = v;
    return true;
  }

  static void append_utf8(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      out += static_cast<char>(0xC0 | (cp >> 6));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += static_cast<char>(0xE0 | (cp >> 12));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (cp >> 18));
      out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  bool skip_string() {
    std::string tmp;  // decoding validates escapes exactly
    return string(tmp);
  }

  bool number(const char** s, const char** e) {
    const char* start = p;
    if (p < end && *p == '-') ++p;
    if (p >= end || *p < '0' || *p > '9') return false;
    while (p < end && *p >= '0' && *p <= '9') ++p;
    if (p < end && *p == '.') {
      ++p;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      ++p;
      if (p < end && (*p == '+' || *p == '-')) ++p;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    *s = start;
    *e = p;
    return true;
  }

  // Skip any JSON value, returning its raw [start,end) slice.
  bool skip_value(const char** s, const char** e) {
    ws();
    *s = p;
    if (p >= end) return false;
    char c = *p;
    if (c == '"') {
      if (!skip_string()) return false;
    } else if (c == '{') {
      ++p;
      ws();
      if (p < end && *p == '}') {
        ++p;
      } else {
        while (true) {
          ws();
          if (!skip_string()) return false;
          ws();
          if (p >= end || *p != ':') return false;
          ++p;
          const char *vs, *ve;
          if (!skip_value(&vs, &ve)) return false;
          ws();
          if (p < end && *p == ',') {
            ++p;
            continue;
          }
          if (p < end && *p == '}') {
            ++p;
            break;
          }
          return false;
        }
      }
    } else if (c == '[') {
      ++p;
      ws();
      if (p < end && *p == ']') {
        ++p;
      } else {
        while (true) {
          const char *vs, *ve;
          if (!skip_value(&vs, &ve)) return false;
          ws();
          if (p < end && *p == ',') {
            ++p;
            continue;
          }
          if (p < end && *p == ']') {
            ++p;
            break;
          }
          return false;
        }
      }
    } else if (c == 't') {
      if (!lit("true")) return false;
    } else if (c == 'f') {
      if (!lit("false")) return false;
    } else if (c == 'n') {
      if (!lit("null")) return false;
    } else {
      const char *ns, *ne;
      if (!number(&ns, &ne)) return false;
    }
    *e = p;
    return true;
  }
};

bool reserved_prefix(const std::string& k) {
  return (!k.empty() && k[0] == '$') || k.rfind("pio_", 0) == 0;
}

// Parse the `properties` object: raw slice + emptiness + reserved-key scan.
// Returns false on malformed JSON (caller marks fallback).
bool parse_properties(Parser& pr, Result& res) {
  pr.ws();
  if (pr.p < pr.end && *pr.p == 'n') {  // null -> treated as {}
    if (!pr.lit("null")) return false;
    res.set(5, "{}", 2);
    res.flags.back() |= kPropsEmpty;
    return true;
  }
  if (pr.p >= pr.end || *pr.p != '{') return false;  // non-object: fallback
  const char* start = pr.p;
  ++pr.p;
  pr.ws();
  bool empty = true;
  std::string key;
  if (pr.p < pr.end && *pr.p == '}') {
    ++pr.p;
  } else {
    while (true) {
      pr.ws();
      if (!pr.string(key)) return false;
      empty = false;
      if (reserved_prefix(key)) {
        res.flags.back() |= kBadPropKey;
        res.set(11, key.data(), key.size());
      }
      pr.ws();
      if (pr.p >= pr.end || *pr.p != ':') return false;
      ++pr.p;
      const char *vs, *ve;
      if (!pr.skip_value(&vs, &ve)) return false;
      pr.ws();
      if (pr.p < pr.end && *pr.p == ',') {
        ++pr.p;
        continue;
      }
      if (pr.p < pr.end && *pr.p == '}') {
        ++pr.p;
        break;
      }
      return false;
    }
  }
  res.set(5, start, static_cast<size_t>(pr.p - start));
  if (empty) res.flags.back() |= kPropsEmpty;
  return true;
}

int key_to_col(const std::string& k) {
  if (k == "event") return 0;
  if (k == "entityType") return 1;
  if (k == "entityId") return 2;
  if (k == "targetEntityType") return 3;
  if (k == "targetEntityId") return 4;
  if (k == "prId") return 7;
  if (k == "eventId") return 8;
  return -1;
}

// Parse one event line into the current row; false -> fallback.
bool parse_line(const char* s, const char* e, Result& res) {
  Parser pr{s, e};
  pr.ws();
  if (pr.p >= pr.end || *pr.p != '{') return false;
  ++pr.p;
  pr.ws();
  if (pr.p < pr.end && *pr.p == '}') {
    ++pr.p;
  } else {
    std::string key, val;
    while (true) {
      pr.ws();
      if (!pr.string(key)) return false;
      pr.ws();
      if (pr.p >= pr.end || *pr.p != ':') return false;
      ++pr.p;
      pr.ws();
      int col = key_to_col(key);
      if (col >= 0) {
        if (pr.p < pr.end && *pr.p == '"') {
          if (!pr.string(val)) return false;
          res.set(col, val.data(), val.size());
        } else if (pr.p < pr.end && *pr.p == 'n') {
          if (!pr.lit("null")) return false;
          // null optional field = absent; null REQUIRED field would make
          // python str(None) -> "None"; that's a validation oddity, punt
          if (col <= 2) return false;
          res.clear_col(col);
        } else if (col <= 2 && pr.p < pr.end &&
                   ((*pr.p >= '0' && *pr.p <= '9') || *pr.p == '-')) {
          // python str()-coerces event/entityType/entityId; an int literal
          // renders identically, floats/exponents may not — ints only
          const char *ns, *ne;
          if (!pr.number(&ns, &ne)) return false;
          for (const char* q = ns; q != ne; ++q)
            if (*q == '.' || *q == 'e' || *q == 'E') return false;
          res.set(col, ns, static_cast<size_t>(ne - ns));
        } else {
          return false;  // unexpected type: python path decides
        }
      } else if (key == "properties") {
        if (!parse_properties(pr, res)) return false;
      } else if (key == "tags") {
        pr.ws();
        if (pr.p < pr.end && *pr.p == 'n') {
          if (!pr.lit("null")) return false;
          res.set(6, "[]", 2);
        } else if (pr.p < pr.end && *pr.p == '[') {
          const char *vs, *ve;
          if (!pr.skip_value(&vs, &ve)) return false;
          res.set(6, vs, static_cast<size_t>(ve - vs));
        } else {
          return false;
        }
      } else if (key == "eventTime" || key == "creationTime") {
        const bool is_event = key[0] == 'e';
        pr.ws();
        double* slot = is_event ? &res.event_time.back()
                                : &res.creation_time.back();
        int raw_col = is_event ? 9 : 10;
        if (pr.p < pr.end && *pr.p == '"') {
          if (!pr.string(val)) return false;
          res.set(raw_col, val.data(), val.size());
          double t;
          std::string v = val;
          if (!v.empty() && v.back() == 'Z') v.pop_back(), v += "+00:00";
          if (iso_to_epoch(v.data(), v.size(), &t)) *slot = t;
          // unparsed: stays NaN with raw present -> python re-parses
        } else if (pr.p < pr.end && *pr.p == 'n') {
          if (!pr.lit("null")) return false;
          res.clear_col(raw_col);
        } else if (pr.p < pr.end &&
                   ((*pr.p >= '0' && *pr.p <= '9') || *pr.p == '-')) {
          const char *ns, *ne;
          if (!pr.number(&ns, &ne)) return false;
          *slot = std::strtod(std::string(ns, ne).c_str(), nullptr) / 1000.0;
          res.set(raw_col, ns, static_cast<size_t>(ne - ns));
        } else {
          return false;
        }
      } else {
        const char *vs, *ve;
        if (!pr.skip_value(&vs, &ve)) return false;
      }
      pr.ws();
      if (pr.p < pr.end && *pr.p == ',') {
        ++pr.p;
        continue;
      }
      if (pr.p < pr.end && *pr.p == '}') {
        ++pr.p;
        break;
      }
      return false;
    }
  }
  pr.ws();
  if (pr.p != pr.end) return false;  // trailing garbage
  // required fields must be present (missing -> python raises the
  // precise "field 'X' is required" error)
  if (!res.cols[0].present.back() || !res.cols[1].present.back() ||
      !res.cols[2].present.back())
    return false;
  return true;
}

}  // namespace

extern "C" {

void* pio_jsonl_parse(const char* buf, int64_t len) {
  auto* res = new Result();
  const char* p = buf;
  const char* end = buf + len;
  int64_t lineno = 0;
  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* le = nl ? nl : end;
    ++lineno;
    // skip blank lines (matches import's `if not line.strip(): continue`)
    const char* q = p;
    while (q < le && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    if (q != le) {
      res->begin_row(p - buf, le - buf, lineno);
      // snapshot column sizes so a half-written row can be rolled back
      size_t saved[kNumCols];
      for (int c = 0; c < kNumCols; ++c) saved[c] = res->cols[c].data.size();
      if (!parse_line(p, le, *res)) {
        for (int c = 0; c < kNumCols; ++c) {
          res->cols[c].data.resize(
              static_cast<size_t>(res->cols[c].offsets.back()));
          res->cols[c].present.back() = 0;
        }
        (void)saved;
        res->event_time.back() = NAN;
        res->creation_time.back() = NAN;
        res->flags.back() = kFallback;
      }
    }
    if (!nl) break;
    p = nl + 1;
  }
  res->finish();
  return res;
}

int64_t pio_jsonl_count(void* h) { return static_cast<Result*>(h)->n; }

int64_t pio_jsonl_col_bytes(void* h, int32_t col) {
  return static_cast<int64_t>(static_cast<Result*>(h)->cols[col].data.size());
}

void pio_jsonl_col_fill(void* h, int32_t col, char* data, int64_t* offsets,
                        uint8_t* present) {
  Col& c = static_cast<Result*>(h)->cols[col];
  if (!c.data.empty()) std::memcpy(data, c.data.data(), c.data.size());
  std::memcpy(offsets, c.offsets.data(), c.offsets.size() * sizeof(int64_t));
  if (!c.present.empty())
    std::memcpy(present, c.present.data(), c.present.size());
}

void pio_jsonl_times(void* h, double* et, double* ct) {
  Result* r = static_cast<Result*>(h);
  std::memcpy(et, r->event_time.data(), r->event_time.size() * sizeof(double));
  std::memcpy(ct, r->creation_time.data(),
              r->creation_time.size() * sizeof(double));
}

void pio_jsonl_flags(void* h, uint8_t* flags) {
  Result* r = static_cast<Result*>(h);
  std::memcpy(flags, r->flags.data(), r->flags.size());
}

void pio_jsonl_lines(void* h, int64_t* start, int64_t* end, int64_t* lineno) {
  Result* r = static_cast<Result*>(h);
  std::memcpy(start, r->line_start.data(), r->line_start.size() * 8);
  std::memcpy(end, r->line_end.data(), r->line_end.size() * 8);
  std::memcpy(lineno, r->lineno.data(), r->lineno.size() * 8);
}

void pio_jsonl_free(void* h) { delete static_cast<Result*>(h); }

// Dictionary-encode one string column: per-row int32 codes in
// first-seen label order (-1 where the column is absent) plus the
// distinct label blob. This is the ingest fast lane that lets training
// reads skip materializing one Python string per row — at 10M+ events
// the per-row str construction dominates the whole read.
struct DictResult {
  std::vector<int32_t> codes;
  std::string blob;               // concatenated distinct labels
  std::vector<int64_t> offsets;   // label k -> [offsets[k], offsets[k+1])
};

void* pio_jsonl_dict_encode(void* h, int32_t col) {
  Result* r = static_cast<Result*>(h);
  const Col& c = r->cols[col];
  auto* d = new DictResult();
  d->codes.resize(static_cast<size_t>(r->n));
  d->offsets.push_back(0);
  std::unordered_map<std::string_view, int32_t> map;
  map.reserve(1024);
  for (int64_t i = 0; i < r->n; ++i) {
    if (!c.present[static_cast<size_t>(i)]) {
      d->codes[static_cast<size_t>(i)] = -1;
      continue;
    }
    std::string_view sv(
        c.data.data() + c.offsets[static_cast<size_t>(i)],
        static_cast<size_t>(c.offsets[static_cast<size_t>(i) + 1] -
                            c.offsets[static_cast<size_t>(i)]));
    auto it = map.find(sv);
    int32_t code;
    if (it == map.end()) {
      code = static_cast<int32_t>(map.size());
      map.emplace(sv, code);
      d->blob.append(sv);
      d->offsets.push_back(static_cast<int64_t>(d->blob.size()));
    } else {
      code = it->second;
    }
    d->codes[static_cast<size_t>(i)] = code;
  }
  return d;
}

int64_t pio_dict_n_labels(void* d) {
  return static_cast<int64_t>(
      static_cast<DictResult*>(d)->offsets.size() - 1);
}

int64_t pio_dict_blob_bytes(void* d) {
  return static_cast<int64_t>(static_cast<DictResult*>(d)->blob.size());
}

void pio_dict_fill(void* dh, int32_t* codes, char* blob, int64_t* offsets) {
  DictResult* d = static_cast<DictResult*>(dh);
  if (!d->codes.empty())
    std::memcpy(codes, d->codes.data(), d->codes.size() * sizeof(int32_t));
  if (!d->blob.empty()) std::memcpy(blob, d->blob.data(), d->blob.size());
  std::memcpy(offsets, d->offsets.data(),
              d->offsets.size() * sizeof(int64_t));
}

void pio_dict_free(void* d) { delete static_cast<DictResult*>(d); }

// Extract one top-level numeric property per row from the raw
// `properties` slices — the training-ingest value column (e.g. "rating")
// without any per-row Python JSON parsing. Per row:
//   status 0 = key absent or JSON null (caller applies default_value)
//   status 1 = numeric; out[i] holds the value
//   status 2 = present but non-numeric (bool/string/object/array —
//              python's isinstance((int,float)) excludes bool)
// Duplicate keys follow json.loads last-wins. Rows whose properties the
// main parse could not express (fallback / absent) report status 0; the
// caller's fallback path re-parses those lines wholesale anyway.
void pio_jsonl_extract_numeric(void* h, const char* key, int64_t keylen,
                               double* out, uint8_t* status) {
  Result* r = static_cast<Result*>(h);
  const Col& c = r->cols[5];
  const std::string want(key, static_cast<size_t>(keylen));
  std::string k;
  for (int64_t i = 0; i < r->n; ++i) {
    out[i] = NAN;
    status[i] = 0;
    if (!c.present[static_cast<size_t>(i)]) continue;
    Parser pr{c.data.data() + c.offsets[static_cast<size_t>(i)],
              c.data.data() + c.offsets[static_cast<size_t>(i) + 1]};
    pr.ws();
    if (pr.p >= pr.end || *pr.p != '{') continue;
    ++pr.p;
    pr.ws();
    if (pr.p < pr.end && *pr.p == '}') continue;
    while (true) {
      pr.ws();
      if (!pr.string(k)) break;
      pr.ws();
      if (pr.p >= pr.end || *pr.p != ':') break;
      ++pr.p;
      pr.ws();
      if (k == want) {
        if (pr.p < pr.end &&
            ((*pr.p >= '0' && *pr.p <= '9') || *pr.p == '-')) {
          const char *ns, *ne;
          if (!pr.number(&ns, &ne)) break;
          out[i] = std::strtod(std::string(ns, ne).c_str(), nullptr);
          status[i] = 1;
        } else if (pr.p < pr.end && *pr.p == 'n') {
          if (!pr.lit("null")) break;
          out[i] = NAN;
          status[i] = 0;
        } else {
          const char *vs, *ve;
          if (!pr.skip_value(&vs, &ve)) break;
          out[i] = NAN;
          status[i] = 2;
        }
      } else {
        const char *vs, *ve;
        if (!pr.skip_value(&vs, &ve)) break;
      }
      pr.ws();
      if (pr.p < pr.end && *pr.p == ',') {
        ++pr.p;
        continue;
      }
      break;
    }
  }
}

}  // extern "C"
