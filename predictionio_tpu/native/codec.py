"""ctypes wrapper for the native JSON-lines event codec.

``parse_jsonl`` returns a :class:`ParsedEvents` batch: per-field python
string lists (None where absent), epoch-second time arrays, and
per-row validation facts pre-computed in C++. Rows the native parser
could not express 1:1 with python semantics carry ``FALLBACK`` and are
re-parsed by the caller with ``Event.from_json`` — so the codec is
always behavior-identical to the python path, only faster.
"""

from __future__ import annotations

import ctypes
import dataclasses
from typing import List, Optional

import numpy as np

from predictionio_tpu import native

# column ids — keep in sync with src/jsonl_codec.cpp
COL_EVENT = 0
COL_ENTITY_TYPE = 1
COL_ENTITY_ID = 2
COL_TARGET_ENTITY_TYPE = 3
COL_TARGET_ENTITY_ID = 4
COL_PROPERTIES = 5
COL_TAGS = 6
COL_PR_ID = 7
COL_EVENT_ID = 8
COL_EVENT_TIME_RAW = 9
COL_CREATION_TIME_RAW = 10
COL_BAD_PROP_KEY = 11

FALLBACK = 1
PROPS_EMPTY = 2
BAD_PROP_KEY = 4


@dataclasses.dataclass
class ParsedEvents:
    """One parsed file: aligned per-row columns."""

    event: List[Optional[str]]
    entity_type: List[Optional[str]]
    entity_id: List[Optional[str]]
    target_entity_type: List[Optional[str]]
    target_entity_id: List[Optional[str]]
    properties_json: List[Optional[str]]   # raw JSON object text
    tags_json: List[Optional[str]]         # raw JSON array text
    pr_id: List[Optional[str]]
    event_id: List[Optional[str]]
    event_time_raw: List[Optional[str]]
    creation_time_raw: List[Optional[str]]
    bad_prop_key: List[Optional[str]]
    event_time: np.ndarray       # float64 epoch sec; NaN = absent/unparsed
    creation_time: np.ndarray
    flags: np.ndarray            # uint8 bitmask per row
    lineno: np.ndarray           # int64 1-based source line numbers
    line_start: np.ndarray       # raw-buffer byte spans (fallback re-parse)
    line_end: np.ndarray
    # numeric-property extraction (ingest value column), when requested:
    # status 0 = absent/null, 1 = numeric (value in prop_value),
    # 2 = present but non-numeric
    prop_value: Optional[np.ndarray] = None   # float64
    prop_status: Optional[np.ndarray] = None  # uint8
    # dictionary encodings (ingest fast lane), when requested:
    # col id -> (codes int32 [n], first-seen distinct labels). A code of
    # -1 means the column is absent on that row.
    dict_codes: Optional[dict] = None
    dict_labels: Optional[dict] = None

    def __len__(self) -> int:
        return len(self.lineno)


def _lib():
    lib = native.load("jsonl_codec")
    # signatures must be (re)applied per CDLL instance — a module-level
    # flag would leave a freshly reloaded handle with the default c_int
    # restype and truncate 64-bit pointers
    if lib is not None and not getattr(lib, "_pio_sigs", False):
        lib.pio_jsonl_parse.restype = ctypes.c_void_p
        lib.pio_jsonl_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.pio_jsonl_count.restype = ctypes.c_int64
        lib.pio_jsonl_count.argtypes = [ctypes.c_void_p]
        lib.pio_jsonl_col_bytes.restype = ctypes.c_int64
        lib.pio_jsonl_col_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.pio_jsonl_col_fill.restype = None
        lib.pio_jsonl_col_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8)]
        lib.pio_jsonl_times.restype = None
        lib.pio_jsonl_times.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double)]
        lib.pio_jsonl_flags.restype = None
        lib.pio_jsonl_flags.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint8)]
        lib.pio_jsonl_lines.restype = None
        lib.pio_jsonl_lines.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.pio_jsonl_free.restype = None
        lib.pio_jsonl_free.argtypes = [ctypes.c_void_p]
        lib.pio_jsonl_extract_numeric.restype = None
        lib.pio_jsonl_extract_numeric.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_uint8)]
        lib.pio_jsonl_dict_encode.restype = ctypes.c_void_p
        lib.pio_jsonl_dict_encode.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int32]
        lib.pio_dict_n_labels.restype = ctypes.c_int64
        lib.pio_dict_n_labels.argtypes = [ctypes.c_void_p]
        lib.pio_dict_blob_bytes.restype = ctypes.c_int64
        lib.pio_dict_blob_bytes.argtypes = [ctypes.c_void_p]
        lib.pio_dict_fill.restype = None
        lib.pio_dict_fill.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
        lib.pio_dict_free.restype = None
        lib.pio_dict_free.argtypes = [ctypes.c_void_p]
        lib._pio_sigs = True
    return lib


def is_available() -> bool:
    return _lib() is not None


def _col(lib, handle, col: int, n: int) -> List[Optional[str]]:
    nbytes = lib.pio_jsonl_col_bytes(handle, col)
    data = ctypes.create_string_buffer(max(1, nbytes))
    offsets = np.empty(n + 1, dtype=np.int64)
    present = np.empty(n, dtype=np.uint8)
    lib.pio_jsonl_col_fill(
        handle, col, data,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        present.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    out: List[Optional[str]] = [None] * n
    idx = np.nonzero(present)[0]
    if len(idx) == 0:
        return out
    blob = data.raw[:nbytes].decode("utf-8")
    # offsets are byte offsets; slice the decoded str directly only when
    # the blob is pure ASCII (byte offsets == char offsets)
    if len(blob) == nbytes:
        off = offsets
        for i in idx:
            out[i] = blob[off[i]:off[i + 1]]
    else:
        raw = data.raw
        for i in idx:
            out[i] = raw[offsets[i]:offsets[i + 1]].decode("utf-8")
    return out


def _dict_encode(lib, handle, col: int, n: int):
    """C++ dictionary encoding of one string column: int32 codes per row
    plus the distinct labels (only DISTINCT values ever become Python
    strings — the 10M-row ingest fast lane)."""
    d = lib.pio_jsonl_dict_encode(handle, col)
    try:
        k = lib.pio_dict_n_labels(d)
        nbytes = lib.pio_dict_blob_bytes(d)
        codes = np.empty(n, dtype=np.int32)
        blob = ctypes.create_string_buffer(max(1, nbytes))
        offsets = np.empty(k + 1, dtype=np.int64)
        lib.pio_dict_fill(
            d, codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        raw = blob.raw[:nbytes]
        labels = np.empty(k, dtype=object)
        for i in range(k):
            labels[i] = raw[offsets[i]:offsets[i + 1]].decode("utf-8")
        return codes, labels
    finally:
        lib.pio_dict_free(d)


def parse_jsonl(data: bytes,
                numeric_property: Optional[str] = None,
                columns: Optional[set] = None,
                dict_encode: Optional[set] = None
                ) -> Optional[ParsedEvents]:
    """Parse a JSON-lines event buffer natively; None if the native lib
    is unavailable (callers use the pure-python path then).

    ``numeric_property`` additionally extracts that top-level properties
    key as a numeric column in C++ (``prop_value``/``prop_status``) — the
    training-ingest value column without per-row Python JSON parsing.

    ``columns`` (COL_* ids) restricts which string columns are
    materialized as Python lists — the per-row str construction is the
    dominant decode cost, so bulk-ingest callers fetch only what they
    read; excluded columns are ``None`` on the result.

    ``dict_encode`` (COL_* ids) returns those columns as int32 codes +
    distinct labels instead of per-row strings (``dict_codes``/
    ``dict_labels``). With ``columns=None`` every NON-encoded column is
    still materialized; an encoded column is additionally materialized
    only if explicitly listed in ``columns``."""
    lib = _lib()
    if lib is None:
        return None
    handle = lib.pio_jsonl_parse(data, len(data))
    try:
        n = lib.pio_jsonl_count(handle)
        enc = dict_encode or set()
        cols = [_col(lib, handle, c, n)
                if (c in columns if columns is not None else c not in enc)
                else None
                for c in range(12)]
        et = np.empty(n, dtype=np.float64)
        ct = np.empty(n, dtype=np.float64)
        lib.pio_jsonl_times(
            handle, et.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ct.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        flags = np.empty(n, dtype=np.uint8)
        lib.pio_jsonl_flags(
            handle, flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        starts = np.empty(n, dtype=np.int64)
        ends = np.empty(n, dtype=np.int64)
        lineno = np.empty(n, dtype=np.int64)
        lib.pio_jsonl_lines(
            handle, starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lineno.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        parsed = ParsedEvents(
            event=cols[COL_EVENT],
            entity_type=cols[COL_ENTITY_TYPE],
            entity_id=cols[COL_ENTITY_ID],
            target_entity_type=cols[COL_TARGET_ENTITY_TYPE],
            target_entity_id=cols[COL_TARGET_ENTITY_ID],
            properties_json=cols[COL_PROPERTIES],
            tags_json=cols[COL_TAGS],
            pr_id=cols[COL_PR_ID],
            event_id=cols[COL_EVENT_ID],
            event_time_raw=cols[COL_EVENT_TIME_RAW],
            creation_time_raw=cols[COL_CREATION_TIME_RAW],
            bad_prop_key=cols[COL_BAD_PROP_KEY],
            event_time=et, creation_time=ct, flags=flags, lineno=lineno,
            line_start=starts, line_end=ends)
        if numeric_property is not None:
            pv = np.empty(n, dtype=np.float64)
            ps = np.empty(n, dtype=np.uint8)
            kb = numeric_property.encode("utf-8")
            lib.pio_jsonl_extract_numeric(
                handle, kb, len(kb),
                pv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                ps.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            parsed.prop_value = pv
            parsed.prop_status = ps
        if enc:
            parsed.dict_codes, parsed.dict_labels = {}, {}
            for c in enc:
                codes, labels = _dict_encode(lib, handle, c, n)
                parsed.dict_codes[c] = codes
                parsed.dict_labels[c] = labels
        return parsed
    finally:
        lib.pio_jsonl_free(handle)


# ---------------------------------------------------------------------------
# Ingest kernels — vectorized merge/pad/bucketize host passes
# (lib ingest_kernels; the 35s monolithic bucketize pass of BENCH_r04).
# Each wrapper returns None when the native lib is unavailable; callers
# fall back to the byte-identical numpy path.
# ---------------------------------------------------------------------------

def _ingest_lib():
    lib = native.load("ingest_kernels")
    # signatures (re)applied per CDLL instance, as in _lib()
    if lib is not None and not getattr(lib, "_pio_sigs", False):
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.pio_merge_runs_i64.restype = None
        lib.pio_merge_runs_i64.argtypes = [
            i64p, i64p, ctypes.c_int32, ctypes.c_int64, i64p]
        lib.pio_bucket_fill.restype = None
        lib.pio_bucket_fill.argtypes = [
            ctypes.c_int64, i64p, i64p,
            ctypes.POINTER(ctypes.c_float), i64p,
            ctypes.POINTER(ctypes.c_int32), i64p, ctypes.c_int32, i64p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float))]
        lib.pio_segment_starts_i64.restype = ctypes.c_int64
        lib.pio_segment_starts_i64.argtypes = [i64p, ctypes.c_int64, i64p]
        lib._pio_sigs = True
    return lib


def ingest_kernels_available() -> bool:
    return _ingest_lib() is not None


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def merge_sorted_runs(keys: np.ndarray,
                      offsets: np.ndarray) -> Optional[np.ndarray]:
    """Stable k-way merge permutation over contiguous sorted int64 runs
    (run r = ``keys[offsets[r]:offsets[r+1]]``, each ascending).
    Bit-identical to ``np.argsort(keys, kind="stable")``; O(N log k)
    instead of a full sort, and the GIL is released for the whole merge.
    None when the native lib is unavailable."""
    lib = _ingest_lib()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = int(keys.shape[0])
    perm = np.empty(n, dtype=np.int64)
    lib.pio_merge_runs_i64(_i64p(keys), _i64p(offsets),
                           len(offsets) - 1, n, _i64p(perm))
    return perm


def segment_starts(sorted_keys: np.ndarray) -> Optional[np.ndarray]:
    """Start index of each equal-key segment in a SORTED int64 array —
    the grouping step of dedup-sum (identical to
    ``np.flatnonzero(np.r_[True, k[1:] != k[:-1]])``). None when the
    native lib is unavailable."""
    lib = _ingest_lib()
    if lib is None:
        return None
    sorted_keys = np.ascontiguousarray(sorted_keys, dtype=np.int64)
    n = int(sorted_keys.shape[0])
    out = np.empty(max(1, n), dtype=np.int64)
    m = lib.pio_segment_starts_i64(_i64p(sorted_keys), n, _i64p(out))
    return out[:m]


def bucket_fill(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                pos: np.ndarray, b_of_row: np.ndarray, rank: np.ndarray,
                tables) -> bool:
    """One-pass scatter of row-sorted deduped triples into per-bucket
    padded tables (``tables`` = list of ``(cols_i32, w_f32, m_f32)``
    C-contiguous zeroed arrays, one per bucket, each ``[Bp, L_b]``).
    Pure data movement — byte-identical to the numpy per-bucket
    mask+scatter, but one pass over N instead of one per bucket.
    False when the native lib is unavailable (caller uses numpy)."""
    lib = _ingest_lib()
    if lib is None:
        return False
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    cols = np.ascontiguousarray(cols, dtype=np.int64)
    vals = np.ascontiguousarray(vals, dtype=np.float32)
    pos = np.ascontiguousarray(pos, dtype=np.int64)
    b_of_row = np.ascontiguousarray(b_of_row, dtype=np.int32)
    rank = np.ascontiguousarray(rank, dtype=np.int64)
    nb = len(tables)
    L = np.asarray([t[0].shape[1] for t in tables], dtype=np.int64)
    c_pp = (ctypes.POINTER(ctypes.c_int32) * nb)(*[
        t[0].ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        for t in tables])
    w_pp = (ctypes.POINTER(ctypes.c_float) * nb)(*[
        t[1].ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        for t in tables])
    m_pp = (ctypes.POINTER(ctypes.c_float) * nb)(*[
        t[2].ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        for t in tables])
    lib.pio_bucket_fill(
        len(rows), _i64p(rows), _i64p(cols),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), _i64p(pos),
        b_of_row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _i64p(rank), nb, _i64p(L), c_pp, w_pp, m_pp)
    return True
