"""e2 — framework-independent algorithm library.

Parity: reference ``e2/src/main/scala/io/prediction/e2/`` (Spark-only,
PIO-independent helpers). Here: numpy/JAX-backed equivalents.
"""

from predictionio_tpu.e2.engine import (  # noqa: F401
    BinaryVectorizer,
    CategoricalNaiveBayes,
    CategoricalNaiveBayesModel,
    LabeledPoint,
    MarkovChain,
    MarkovChainModel,
)
from predictionio_tpu.e2.evaluation import split_data  # noqa: F401
from predictionio_tpu.e2.forest import (  # noqa: F401
    RandomForestModel,
    train_classifier,
)
