"""Random-forest classifier — the MLlib ``RandomForest.trainClassifier``
capability (invoked by the reference classification template's
``add-algorithm/src/main/scala/RandomForestAlgorithm.scala:28-41``).

Not a port of MLlib's distributed tree induction: this is a vectorized
host-side implementation shaped for the framework's workloads (tabular
features extracted from entity properties — thousands of rows, a handful
of features). Split search is one sorted prefix-count pass per
(node, feature): all candidate thresholds are scored at once from
cumulative class counts, no Python loop over cut points. Trees are
grown depth-first to ``max_depth``; per-tree bootstrap sampling and
per-node feature subsetting give the usual variance reduction.

Params mirror the reference's ``RandomForestAlgorithmParams`` 1:1
(num_classes, num_trees, feature_subset_strategy, impurity, max_depth,
max_bins).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class _Tree:
    """Flat array-form binary tree (index 0 = root; -1 child = leaf)."""

    feature: np.ndarray     # int32 [n_nodes] split feature (-1 = leaf)
    threshold: np.ndarray   # float64 [n_nodes] go left if x <= t (same
    #                         precision the split search partitioned with)
    left: np.ndarray        # int32 [n_nodes]
    right: np.ndarray       # int32 [n_nodes]
    leaf_class: np.ndarray  # int32 [n_nodes] argmax class at the node

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Vectorized traversal: every sample walks one level per step."""
        node = np.zeros(len(X), dtype=np.int32)
        while True:
            feat = self.feature[node]
            active = feat >= 0
            if not active.any():
                break
            f = np.where(active, feat, 0)
            go_left = X[np.arange(len(X)), f] <= self.threshold[node]
            nxt = np.where(go_left, self.left[node], self.right[node])
            node = np.where(active, nxt, node)
        return self.leaf_class[node]


def _impurity_from_counts(counts: np.ndarray, impurity: str) -> np.ndarray:
    """counts [..., C] -> impurity [...] (gini or entropy)."""
    n = counts.sum(axis=-1, keepdims=True)
    p = counts / np.maximum(n, 1)
    if impurity == "entropy":
        with np.errstate(divide="ignore", invalid="ignore"):
            h = -np.where(p > 0, p * np.log2(p), 0.0).sum(axis=-1)
        return h
    return 1.0 - (p * p).sum(axis=-1)


def _best_split(X: np.ndarray, y: np.ndarray, n_classes: int,
                feat_idx: np.ndarray, impurity: str, max_bins: int
                ) -> Optional[Tuple[int, float]]:
    """Best (feature, threshold) by weighted impurity decrease, scoring
    EVERY cut of each candidate feature in one prefix-count pass."""
    n = len(y)
    onehot = np.eye(n_classes, dtype=np.float64)[y]
    total = onehot.sum(axis=0)
    parent_imp = float(_impurity_from_counts(total, impurity))
    if parent_imp <= 0:
        return None
    best: Optional[Tuple[float, int, float]] = None
    for f in feat_idx:
        xs = X[:, f]
        order = np.argsort(xs, kind="stable")
        xsorted = xs[order]
        left = np.cumsum(onehot[order], axis=0)        # [n, C]
        # cut i = left gets rows 0..i; valid only between distinct values
        valid = xsorted[:-1] < xsorted[1:]
        if not valid.any():
            continue
        cuts = np.nonzero(valid)[0]
        if len(cuts) > max_bins:                       # bin the cut set
            cuts = cuts[np.linspace(0, len(cuts) - 1, max_bins,
                                    dtype=np.int64)]
        nl = (cuts + 1).astype(np.float64)
        lc = left[cuts]
        rc = total[None, :] - lc
        gain = parent_imp - (
            nl * _impurity_from_counts(lc, impurity)
            + (n - nl) * _impurity_from_counts(rc, impurity)) / n
        gx = int(np.argmax(gain))
        if gain[gx] > 1e-12:
            t = float((xsorted[cuts[gx]] + xsorted[cuts[gx] + 1]) / 2.0)
            if best is None or gain[gx] > best[0]:
                best = (float(gain[gx]), int(f), t)
    if best is None:
        return None
    return best[1], best[2]


def _n_sub_features(strategy: str, d: int) -> int:
    """MLlib featureSubsetStrategy semantics: 'auto' = sqrt for
    classification; 'all', 'sqrt', 'log2', 'onethird' as named.
    Unknown strategies raise, as MLlib's enum validation does."""
    s = strategy.lower()
    if s in ("auto", "sqrt"):
        return max(1, int(np.sqrt(d)))
    if s == "log2":
        return max(1, int(np.log2(d)))
    if s == "onethird":
        return max(1, d // 3)
    if s == "all":
        return d
    raise ValueError(
        f"unsupported feature_subset_strategy {strategy!r}; use "
        "auto|all|sqrt|log2|onethird")


def _grow(X: np.ndarray, y: np.ndarray, n_classes: int,
          rng: np.random.Generator, max_depth: int, max_bins: int,
          n_sub: int, impurity: str) -> _Tree:
    feature: List[int] = []
    threshold: List[float] = []
    left: List[int] = []
    right: List[int] = []
    leaf_class: List[int] = []

    def node(idx: np.ndarray, depth: int) -> int:
        me = len(feature)
        counts = np.bincount(y[idx], minlength=n_classes)
        feature.append(-1)
        threshold.append(0.0)
        left.append(-1)
        right.append(-1)
        leaf_class.append(int(np.argmax(counts)))
        if depth >= max_depth or len(idx) < 2:
            return me
        feats = rng.choice(X.shape[1], size=n_sub, replace=False)
        split = _best_split(X[idx], y[idx], n_classes, feats, impurity,
                            max_bins)
        if split is None:
            return me
        f, t = split
        go_left = X[idx, f] <= t
        if not go_left.any() or go_left.all():
            return me
        feature[me] = f
        threshold[me] = t
        left[me] = node(idx[go_left], depth + 1)
        right[me] = node(idx[~go_left], depth + 1)
        return me

    node(np.arange(len(y)), 0)
    return _Tree(np.asarray(feature, dtype=np.int32),
                 np.asarray(threshold, dtype=np.float64),
                 np.asarray(left, dtype=np.int32),
                 np.asarray(right, dtype=np.int32),
                 np.asarray(leaf_class, dtype=np.int32))


@dataclasses.dataclass
class RandomForestModel:
    """Majority-vote forest (RandomForestModel.predict analog)."""

    trees: List[_Tree]
    n_classes: int

    def predict(self, features) -> float:
        return float(self.predict_batch(
            np.asarray(features, dtype=np.float64)[None, :])[0])

    def predict_batch(self, X: np.ndarray) -> np.ndarray:
        votes = np.zeros((len(X), self.n_classes), dtype=np.int64)
        for t in self.trees:
            votes[np.arange(len(X)), t.predict(X)] += 1
        return votes.argmax(axis=1).astype(np.float64)


def train_classifier(X: np.ndarray, y: np.ndarray, *,
                     num_classes: int, num_trees: int = 10,
                     feature_subset_strategy: str = "auto",
                     impurity: str = "gini", max_depth: int = 5,
                     max_bins: int = 32,
                     seed: Optional[int] = None) -> RandomForestModel:
    """``RandomForest.trainClassifier`` parity entry: bootstrap-sampled,
    feature-subset trees, majority vote."""
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.int64)
    if X.ndim != 2 or len(X) != len(y):
        raise ValueError(f"bad shapes: X {X.shape}, y {y.shape}")
    if len(X) == 0:
        raise ValueError("cannot train a forest on zero samples")
    if y.min() < 0 or y.max() >= num_classes:
        raise ValueError(
            f"labels must be in [0, {num_classes}); got "
            f"[{y.min()}, {y.max()}]")
    if impurity not in ("gini", "entropy"):
        raise ValueError(f"unsupported impurity {impurity!r}")
    if not 1 <= max_depth <= 30:  # MLlib's own depth cap
        raise ValueError(f"max_depth must be in [1, 30], got {max_depth}")
    if num_trees < 1:
        raise ValueError(f"num_trees must be >= 1, got {num_trees}")
    if max_bins < 2:
        raise ValueError(f"max_bins must be >= 2, got {max_bins}")
    rng = np.random.default_rng(seed)
    n_sub = _n_sub_features(feature_subset_strategy, X.shape[1])
    trees = []
    for _ in range(num_trees):
        boot = rng.integers(0, len(X), size=len(X))
        trees.append(_grow(X[boot], y[boot], num_classes, rng, max_depth,
                           max_bins, n_sub, impurity))
    return RandomForestModel(trees, num_classes)
