"""k-fold cross-validation splitting.

Parity: ``e2/.../evaluation/CrossValidation.scala:33-64``
(``CommonHelperFunctions.splitData``): fold ``f`` tests on points where
``idx % k == f`` and trains on the rest.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(
    eval_k: int,
    dataset: Sequence[D],
    evaluator_info: EI,
    training_data_creator: Callable[[List[D]], TD],
    query_creator: Callable[[D], Q],
    actual_creator: Callable[[D], A],
) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
    """Split into eval_k folds; returns [(TD, EI, [(Q, A)])] — the shape
    ``read_eval`` wants."""
    if eval_k < 1:
        raise ValueError(f"eval_k must be >= 1, got {eval_k}")
    out = []
    for fold in range(eval_k):
        training = [pt for idx, pt in enumerate(dataset)
                    if idx % eval_k != fold]
        testing = [pt for idx, pt in enumerate(dataset)
                   if idx % eval_k == fold]
        out.append((
            training_data_creator(training),
            evaluator_info,
            [(query_creator(d), actual_creator(d)) for d in testing],
        ))
    return out
