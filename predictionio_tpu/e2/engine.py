"""e2 engine helpers: categorical naive Bayes, Markov chain, one-hot
vectorizer.

Parity targets (semantics matched, Spark shapes replaced by numpy):

- ``e2/.../engine/CategoricalNaiveBayes.scala:29-176`` — model = log
  priors + per-feature-slot log likelihood maps; ``log_score`` with a
  pluggable default likelihood for unseen values; ``predict`` = argmax.
  The ``combineByKey`` tally becomes one vectorized ``np.add.at`` over
  integer-encoded labels/values.
- ``e2/.../engine/MarkovChain.scala:32-89`` — top-N row-normalized
  transition matrix from a sparse tally; ``predict`` = vector-matrix
  product (dense matmul here: one MXU-friendly op instead of an RDD map).
- ``e2/.../engine/BinaryVectorizer.scala:24-61`` — (property, value) →
  index one-hot encoder.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """One data point (CategoricalNaiveBayes.scala:155-176)."""

    label: str
    features: Tuple[str, ...]

    def __post_init__(self):
        if not isinstance(self.features, tuple):
            object.__setattr__(self, "features", tuple(self.features))


DefaultLikelihood = Callable[[Sequence[float]], float]


def _neg_inf_default(likelihoods: Sequence[float]) -> float:
    return float("-inf")


class CategoricalNaiveBayesModel:
    """NB over categorical string features.

    ``priors``: label -> log P(label); ``likelihoods``: label -> one
    dict per feature slot mapping value -> log P(value | label)
    (CategoricalNaiveBayesModel, CategoricalNaiveBayes.scala:88-153).
    """

    def __init__(self, priors: Mapping[str, float],
                 likelihoods: Mapping[str, Sequence[Mapping[str, float]]]):
        self.priors = dict(priors)
        self.likelihoods = {
            label: [dict(slot) for slot in slots]
            for label, slots in likelihoods.items()
        }
        first = next(iter(self.likelihoods.values()))
        self.feature_count = len(first)

    def log_score(
        self, point: LabeledPoint,
        default_likelihood: DefaultLikelihood = _neg_inf_default,
    ) -> Optional[float]:
        """Log score of (label, features); None for an unknown label
        (CategoricalNaiveBayes.scala:104-116)."""
        if point.label not in self.priors:
            return None
        return self._log_score(point.label, point.features,
                               default_likelihood)

    def _log_score(self, label: str, features: Sequence[str],
                   default_likelihood: DefaultLikelihood) -> float:
        likelihood = self.likelihoods[label]
        total = self.priors[label]
        for feature, slot in zip(features, likelihood):
            if feature in slot:
                total += slot[feature]
            else:
                total += default_likelihood(list(slot.values()))
        return total

    def predict(self, features: Sequence[str]) -> str:
        """argmax label (CategoricalNaiveBayes.scala:140-152)."""
        return max(
            self.priors,
            key=lambda label: self._log_score(
                label, features, _neg_inf_default))

    def predict_batch(self, features: Sequence[Sequence[str]]) -> List[str]:
        """Vectorized argmax over many points: integer-encode values once,
        then a single gather + sum per label — the TPU-friendly batch path
        the reference lacks."""
        labels = sorted(self.priors)
        scores = np.zeros((len(features), len(labels)), dtype=np.float64)
        for lx, label in enumerate(labels):
            slots = self.likelihoods[label]
            scores[:, lx] = self.priors[label]
            for n, point in enumerate(features):
                for feature, slot in zip(point, slots):
                    scores[n, lx] += slot.get(feature, float("-inf"))
        return [labels[i] for i in np.argmax(scores, axis=1)]


class CategoricalNaiveBayes:
    """Trainer (CategoricalNaiveBayes.scala:29-79)."""

    @staticmethod
    def train(points: Sequence[LabeledPoint]) -> CategoricalNaiveBayesModel:
        if not points:
            raise ValueError("cannot train on an empty data set")
        n_slots = len(points[0].features)
        labels = sorted({p.label for p in points})
        label_ix = {l: i for i, l in enumerate(labels)}
        vocabs: List[Dict[str, int]] = []
        for s in range(n_slots):
            values = sorted({p.features[s] for p in points})
            vocabs.append({v: i for i, v in enumerate(values)})

        label_codes = np.fromiter((label_ix[p.label] for p in points),
                                  dtype=np.int64, count=len(points))
        label_counts = np.bincount(label_codes, minlength=len(labels))

        likelihoods: Dict[str, List[Dict[str, float]]] = {
            l: [] for l in labels}
        for s, vocab in enumerate(vocabs):
            value_codes = np.fromiter(
                (vocab[p.features[s]] for p in points),
                dtype=np.int64, count=len(points))
            counts = np.zeros((len(labels), len(vocab)), dtype=np.int64)
            np.add.at(counts, (label_codes, value_codes), 1)
            with np.errstate(divide="ignore"):
                log_lik = np.log(counts / label_counts[:, None])
            for l, lx in label_ix.items():
                likelihoods[l].append({
                    v: float(log_lik[lx, vx])
                    for v, vx in vocab.items() if counts[lx, vx] > 0
                })

        total = float(label_counts.sum())
        priors = {
            l: math.log(label_counts[lx] / total)
            for l, lx in label_ix.items()
        }
        return CategoricalNaiveBayesModel(priors, likelihoods)


class MarkovChainModel:
    """Row-stochastic top-N transition matrix (MarkovChain.scala:57-89).

    Stored dense [S, S] float32 — at e2 scale a dense matmul beats the
    reference's per-row RDD sparse products and maps onto the MXU.
    """

    def __init__(self, transition: np.ndarray, n: int):
        self.transition = np.asarray(transition, dtype=np.float32)
        self.n = n

    def predict(self, current_state: Sequence[float]) -> np.ndarray:
        """Next-state distribution = state · P (MarkovChain.scala:70-88)."""
        s = np.asarray(current_state, dtype=np.float32)
        return s @ self.transition


class MarkovChain:
    """Trainer (MarkovChain.scala:32-55)."""

    @staticmethod
    def train(rows: Sequence[int], cols: Sequence[int],
              values: Sequence[float], n_states: int,
              top_n: int) -> MarkovChainModel:
        """Tally entries (row, col, count) -> keep each row's top-N by
        count, normalized by the row's FULL total (matches the reference:
        sum over all entries, then take(topN))."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        values = np.asarray(values, dtype=np.float64)
        counts = np.zeros((n_states, n_states), dtype=np.float64)
        np.add.at(counts, (rows, cols), values)

        totals = counts.sum(axis=1, keepdims=True)
        transition = np.zeros_like(counts)
        nonzero = totals[:, 0] > 0
        if top_n < n_states:
            # zero everything below each row's top-N tally
            kth = np.partition(counts, -top_n, axis=1)[:, -top_n][:, None]
            keep = counts >= kth
            # ties at the threshold: cap to exactly top_n per row, matching
            # the reference's take(topN) after a stable sort
            for r in np.nonzero(keep.sum(axis=1) > top_n)[0]:
                order = np.argsort(-counts[r], kind="stable")[:top_n]
                mask = np.zeros(n_states, dtype=bool)
                mask[order] = True
                keep[r] = mask
            counts = np.where(keep, counts, 0.0)
        transition[nonzero] = counts[nonzero] / totals[nonzero]
        return MarkovChainModel(transition.astype(np.float32), top_n)


class BinaryVectorizer:
    """(property, value) -> one-hot index (BinaryVectorizer.scala:24-61)."""

    def __init__(self, property_map: Mapping[Tuple[str, str], int]):
        self.property_map = dict(property_map)
        self.num_features = len(self.property_map)
        self.properties = [
            kv for kv, _ in sorted(self.property_map.items(),
                                   key=lambda e: e[1])
        ]

    def __str__(self) -> str:
        pairs = ",".join(f"({p}, {v})" for p, v in self.properties)
        return f"BinaryVectorizer({self.num_features}): {pairs}"

    def to_binary(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        vec = np.zeros(self.num_features, dtype=np.float32)
        for pair in pairs:
            idx = self.property_map.get(tuple(pair))
            if idx is not None:
                vec[idx] = 1.0
        return vec

    def to_binary_batch(
            self, rows: Sequence[Sequence[Tuple[str, str]]]) -> np.ndarray:
        out = np.zeros((len(rows), self.num_features), dtype=np.float32)
        for i, pairs in enumerate(rows):
            out[i] = self.to_binary(pairs)
        return out

    @classmethod
    def from_maps(cls, maps: Sequence[Mapping[str, str]],
                  properties: Sequence[str]) -> "BinaryVectorizer":
        """Distinct (property, value) pairs restricted to ``properties``
        (BinaryVectorizer.scala:45-55)."""
        wanted = set(properties)
        seen: Dict[Tuple[str, str], int] = {}
        for m in maps:
            for k, v in m.items():
                if k in wanted and (k, v) not in seen:
                    seen[(k, v)] = len(seen)
        return cls(seen)

    @classmethod
    def from_pairs(
            cls, pairs: Sequence[Tuple[str, str]]) -> "BinaryVectorizer":
        return cls({tuple(p): i for i, p in enumerate(pairs)})
