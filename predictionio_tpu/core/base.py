"""Base contracts for the DASE pipeline.

Parity targets (behavior, not structure):
- ``BaseDataSource.readTrainingBase/readEvalBase`` (reference
  ``core/.../core/BaseDataSource.scala:40,51``)
- ``BasePreparator.prepareBase`` (``BasePreparator.scala``)
- ``BaseAlgorithm.trainBase/batchPredictBase/predictBase/
  makePersistentModel`` (``BaseAlgorithm.scala:66-122``)
- ``BaseServing.supplementBase/serveBase`` (``BaseServing.scala``)
- ``AbstractDoer``/``Doer`` factory (``AbstractDoer.scala:32-65``) — here a
  plain constructor call: controllers take one ``params`` argument.
- Workflow control: sanity checks and stop-after interruptions
  (``Engine.scala:649-687``, ``WorkflowUtils.scala:411-415``).

Type parameters from the reference map to duck-typed Python values:
TD training data, EI evaluation info, PD prepared data, Q query,
P prediction, A actual. Spark RDDs become host values (lists / numpy /
jax arrays) that algorithms shard onto the mesh via the ComputeContext.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import (
    Any, Callable, Dict, List, Optional, Protocol, Sequence, Tuple,
    runtime_checkable,
)

from predictionio_tpu.core.context import ComputeContext


class Params:
    """Marker base for controller hyper-parameter bundles
    (``Params.scala:23``). Use ``@dataclass`` subclasses."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """``EmptyParams()`` (Params.scala:29)."""


@dataclasses.dataclass
class WorkflowParams:
    """Training-process controls (``WorkflowParams`` in Workflow.scala).

    ``stop_after_read``/``stop_after_prepare`` reproduce the CLI debug
    interruptions (``Engine.scala:663-687``).
    """

    batch: str = ""
    verbose: int = 2
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    # Worker threads for param-set evaluation (the reference's `.par`
    # sweeps, MetricEvaluator.scala:221-230 / FastEvalEngine.scala:176).
    # 0 -> a CPU-count-based default (PARALLEL, like the reference), so
    # user controllers/metrics must tolerate concurrent param-set
    # evaluation — exactly as they must under Spark/.par there; set 1 to
    # force a serial sweep for thread-unsafe user code.
    eval_parallelism: int = 0


class TrainingInterruption(Exception):
    """Base for deliberate workflow interruptions (WorkflowUtils.scala:411)."""


class StopAfterReadInterruption(TrainingInterruption):
    pass


class StopAfterPrepareInterruption(TrainingInterruption):
    pass


@runtime_checkable
class SanityCheck(Protocol):
    """Objects opting into data sanity checking (``SanityCheck.scala``):
    ``sanity_check`` raises on bad data."""

    def sanity_check(self) -> None: ...


def run_sanity_check(obj: Any) -> None:
    """Perform the check iff the object supports it (Engine.scala:649-661)."""
    if isinstance(obj, SanityCheck):
        obj.sanity_check()


# ---------------------------------------------------------------------------
# Model persistence sentinels (BaseAlgorithm.scala:107-112 three modes)
# ---------------------------------------------------------------------------

class _Retrain:
    """Sentinel: model was not persisted; retrain at deploy
    (the reference returns Unit, ``Engine.scala:208-230``)."""

    _instance: Optional["_Retrain"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "RETRAIN"

    def __reduce__(self):  # pickles to the singleton
        return (_Retrain, ())


RETRAIN = _Retrain()


@dataclasses.dataclass(frozen=True)
class PersistentModelManifest:
    """Marker persisted in place of a custom-saved model
    (``PersistentModelManifest`` in PersistentModel workflow); ``class_path``
    is ``module:Class`` of the PersistentModel implementation."""

    class_path: str


# ---------------------------------------------------------------------------
# Controller bases
# ---------------------------------------------------------------------------

class AbstractDoer:
    """Controllers are constructed with exactly one ``params`` argument
    (AbstractDoer.scala:32). Subclasses may declare ``params_class`` for
    typed JSON extraction."""

    params_class: Optional[type] = None

    def __init__(self, params: Optional[Params] = None):
        self.params = params if params is not None else EmptyParams()


def Doer(clazz: type, params: Optional[Params] = None) -> Any:
    """Instantiate a controller with params (``Doer.apply``,
    AbstractDoer.scala:47-65)."""
    return clazz(params)


class BaseDataSource(AbstractDoer, abc.ABC):
    """Reads training and evaluation data (BaseDataSource.scala:33-58)."""

    @abc.abstractmethod
    def read_training_base(self, ctx: ComputeContext) -> Any:
        """Return TD."""

    def read_eval_base(
        self, ctx: ComputeContext
    ) -> Sequence[Tuple[Any, Any, Sequence[Tuple[Any, Any]]]]:
        """Return eval sets ``[(TD, EI, [(Q, A), ...]), ...]``; default none
        (BaseDataSource.scala:51-56 returns empty)."""
        return []


class BasePreparator(AbstractDoer, abc.ABC):
    """TD -> PD (BasePreparator.scala:33-44)."""

    @abc.abstractmethod
    def prepare_base(self, ctx: ComputeContext, td: Any) -> Any: ...


class BaseAlgorithm(AbstractDoer, abc.ABC):
    """The central contract (BaseAlgorithm.scala:36-122)."""

    @abc.abstractmethod
    def train_base(self, ctx: ComputeContext, pd: Any) -> Any:
        """PD -> model."""

    @abc.abstractmethod
    def batch_predict_base(
        self, ctx: ComputeContext, model: Any,
        indexed_queries: Sequence[Tuple[int, Any]],
    ) -> List[Tuple[int, Any]]:
        """Predict for indexed queries (evaluation path,
        BaseAlgorithm.scala:78-88)."""

    @abc.abstractmethod
    def predict_base(self, model: Any, query: Any) -> Any:
        """Single-query predict (serving path, BaseAlgorithm.scala:90-98)."""

    def make_persistent_model(self, ctx: ComputeContext, model_id: str,
                              algo_params: Params, model: Any) -> Any:
        """Convert a trained model into its persisted form: the model itself
        (automatic serialization), a PersistentModelManifest (custom save), or
        RETRAIN (re-train at deploy). Default: do not persist
        (BaseAlgorithm.scala:107-112 returns Unit)."""
        return RETRAIN

    @property
    def query_class(self) -> Optional[type]:
        """Query type for JSON extraction at serving time
        (BaseAlgorithm.scala:118-122); None means raw dict queries."""
        return getattr(self, "query_cls", None)


class BaseServing(AbstractDoer, abc.ABC):
    """Query supplement + prediction combination (BaseServing.scala:33-48)."""

    def supplement_base(self, query: Any) -> Any:
        return query

    @abc.abstractmethod
    def serve_base(self, query: Any, predictions: Sequence[Any]) -> Any: ...


class BaseEvaluatorResult:
    """Evaluation output renderings (BaseEvaluatorResult.scala:57-72)."""

    #: When True the result is not persisted (FakeWorkflow uses this).
    no_save: bool = False

    def to_one_liner(self) -> str:
        return ""

    def to_html(self) -> str:
        return ""

    def to_json(self) -> str:
        return ""


class BaseEvaluator(AbstractDoer, abc.ABC):
    """Scores eval output (BaseEvaluator.scala:49)."""

    @abc.abstractmethod
    def evaluate_base(self, ctx: ComputeContext, evaluation: Any,
                      engine_eval_data_set: Sequence[Tuple[Any, Any]],
                      params: WorkflowParams) -> BaseEvaluatorResult: ...
