"""ComputeContext — the TPU-native replacement for SparkContext.

The reference threads a SparkContext through every controller call
(``core/.../core/BaseDataSource.scala:40``, ``BaseAlgorithm.scala:66``).
Here the equivalent handle is a jax device mesh plus workflow metadata:
controllers that shard work across chips receive the mesh and annotate
shardings; local controllers ignore it. jax is imported lazily so
storage-only tooling doesn't pay the import cost.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple


@dataclasses.dataclass
class ComputeContext:
    """Devices + mesh handle passed to every DASE stage.

    ``mesh_shape``/``axis_names`` describe the logical mesh laid over
    ``devices``; ``mesh`` materializes a ``jax.sharding.Mesh``. ``mode``
    mirrors the reference WorkflowContext app-name tagging
    (``WorkflowContext.scala:26-43``): "train" | "eval" | "serving".
    """

    mode: str = "train"
    batch: str = ""
    mesh_shape: Optional[Tuple[int, ...]] = None
    axis_names: Tuple[str, ...] = ("data",)
    _devices: Optional[Sequence[Any]] = None
    _mesh: Any = None

    @property
    def devices(self) -> Sequence[Any]:
        if self._devices is None:
            import jax

            self._devices = tuple(jax.devices())
        return self._devices

    @property
    def device_count(self) -> int:
        return len(self.devices)

    @property
    def mesh(self):
        """Materialize (and cache) the jax Mesh for this context."""
        if self._mesh is None:
            import numpy as np
            import jax

            devs = np.asarray(self.devices)
            shape = self.mesh_shape or (len(devs),)
            names = self.axis_names
            if len(shape) != len(names):
                names = tuple(f"axis{i}" for i in range(len(shape)))
            self._mesh = jax.sharding.Mesh(devs.reshape(shape), names)
        return self._mesh

    def replace(self, **kw) -> "ComputeContext":
        return dataclasses.replace(self, **kw)

    def stop(self) -> None:
        """Release the mesh handle (SparkContext.stop analog; jax devices
        themselves are process-global so there is nothing else to free)."""
        self._mesh = None


def workflow_context(mode: str = "train", batch: str = "",
                     mesh_shape: Optional[Tuple[int, ...]] = None,
                     axis_names: Tuple[str, ...] = ("data",),
                     devices: Optional[Sequence[Any]] = None
                     ) -> ComputeContext:
    """Factory mirroring ``WorkflowContext.apply`` (WorkflowContext.scala:26)."""
    return ComputeContext(mode=mode, batch=batch, mesh_shape=mesh_shape,
                          axis_names=axis_names, _devices=devices)
