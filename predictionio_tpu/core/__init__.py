"""Core base abstractions for the DASE pipeline.

Parity targets: reference ``core/src/main/scala/io/prediction/core/``
(BaseEngine, BaseDataSource, BasePreparator, BaseAlgorithm, BaseServing,
AbstractDoer) — redesigned for a TPU host process: SparkContext is replaced
by :class:`ComputeContext` (a jax device mesh + config), RDDs by host
arrays/lists that the data plane shards onto the mesh.
"""

from predictionio_tpu.core.base import (
    RETRAIN,
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
    Doer,
    EmptyParams,
    Params,
    PersistentModelManifest,
    SanityCheck,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    TrainingInterruption,
    WorkflowParams,
)
from predictionio_tpu.core.context import ComputeContext

__all__ = [
    "RETRAIN",
    "BaseAlgorithm",
    "BaseDataSource",
    "BasePreparator",
    "BaseServing",
    "ComputeContext",
    "Doer",
    "EmptyParams",
    "Params",
    "PersistentModelManifest",
    "SanityCheck",
    "StopAfterPrepareInterruption",
    "StopAfterReadInterruption",
    "TrainingInterruption",
    "WorkflowParams",
]
