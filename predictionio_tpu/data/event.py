"""Canonical event model + validation.

Parity target: reference ``data/src/main/scala/io/prediction/data/storage/
Event.scala`` — same 11 fields, same validation rules (Event.scala:109-177):

- event / entityType / entityId must be non-empty
- targetEntityType and targetEntityId: both present or both absent, non-empty
- ``$unset`` must carry non-empty properties
- a reserved-prefix event name (``$`` or ``pio_``) must be one of the special
  events ``$set/$unset/$delete``
- special events cannot have a target entity
- reserved-prefix entity types only if built-in (``pio_pr``)
- property names must not use the reserved ``pio_``/``$`` prefix
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import uuid
from typing import Any, Mapping, Optional, Sequence, Tuple

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.utils.compat import parse_iso8601

UTC = _dt.timezone.utc

SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})
BUILTIN_PROPERTIES: frozenset = frozenset()


class EventValidationError(ValueError):
    """Raised when an Event violates the validation rules."""


def _now() -> _dt.datetime:
    return _dt.datetime.now(tz=UTC)


@dataclasses.dataclass(frozen=True)
class Event:
    """One immutable event (cf. Event.scala:39-57).

    ``properties`` accepts any mapping and is normalized to a DataMap.
    """

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: _dt.datetime = dataclasses.field(default_factory=_now)
    tags: Tuple[str, ...] = ()
    pr_id: Optional[str] = None
    creation_time: _dt.datetime = dataclasses.field(default_factory=_now)
    event_id: Optional[str] = None

    def __post_init__(self):
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        if isinstance(self.tags, list):
            object.__setattr__(self, "tags", tuple(self.tags))
        for attr in ("event_time", "creation_time"):
            t = getattr(self, attr)
            if t.tzinfo is None:
                object.__setattr__(self, attr, t.replace(tzinfo=UTC))

    def with_id(self, event_id: str) -> "Event":
        return dataclasses.replace(self, event_id=event_id)

    # -- wire format (EventJson4sSupport.APISerializer parity) -------------
    def to_dict(self) -> dict:
        d: dict = {
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
            "properties": self.properties.fields,
            "eventTime": _fmt_time(self.event_time),
            "creationTime": _fmt_time(self.creation_time),
        }
        if self.event_id is not None:
            d["eventId"] = self.event_id
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = self.target_entity_id
        if self.tags:
            d["tags"] = list(self.tags)
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Event":
        if "event" not in d:
            raise EventValidationError("field 'event' is required")
        if "entityType" not in d:
            raise EventValidationError("field 'entityType' is required")
        if "entityId" not in d:
            raise EventValidationError("field 'entityId' is required")
        now = _now()
        ev = cls(
            event=str(d["event"]),
            entity_type=str(d["entityType"]),
            entity_id=str(d["entityId"]),
            target_entity_type=d.get("targetEntityType"),
            target_entity_id=d.get("targetEntityId"),
            properties=DataMap(d.get("properties") or {}),
            event_time=_parse_time(d.get("eventTime")) or now,
            tags=tuple(d.get("tags") or ()),
            pr_id=d.get("prId"),
            creation_time=_parse_time(d.get("creationTime")) or now,
            event_id=d.get("eventId"),
        )
        return ev

    @classmethod
    def from_json(cls, s: str) -> "Event":
        try:
            d = json.loads(s)
        except json.JSONDecodeError as e:
            raise EventValidationError(f"invalid JSON: {e}") from e
        if not isinstance(d, dict):
            raise EventValidationError("event JSON must be an object")
        return cls.from_dict(d)


def _fmt_time(t: _dt.datetime) -> str:
    return t.astimezone(UTC).isoformat()


def _parse_time(v: Any) -> Optional[_dt.datetime]:
    if v is None:
        return None
    if isinstance(v, _dt.datetime):
        return v if v.tzinfo else v.replace(tzinfo=UTC)
    if isinstance(v, (int, float)):
        return _dt.datetime.fromtimestamp(v / 1000.0, tz=UTC)
    try:
        t = parse_iso8601(str(v))
    except ValueError as e:
        raise EventValidationError(f"invalid time: {v!r}") from e
    return t if t.tzinfo else t.replace(tzinfo=UTC)


def is_reserved_prefix(name: str) -> bool:
    """Event.scala:74-75 — names starting with ``$`` or ``pio_`` are reserved."""
    return name.startswith("$") or name.startswith("pio_")


def is_special_event(name: str) -> bool:
    return name in SPECIAL_EVENTS


def validate_event(e: Event) -> None:
    """Validation rules, 1:1 with EventValidation.validate (Event.scala:109-138)."""
    def req(cond: bool, msg: str) -> None:
        if not cond:
            raise EventValidationError(msg)

    req(bool(e.event), "event must not be empty.")
    req(bool(e.entity_type), "entityType must not be empty string.")
    req(bool(e.entity_id), "entityId must not be empty string.")
    req(e.target_entity_type != "", "targetEntityType must not be empty string")
    req(e.target_entity_id != "", "targetEntityId must not be empty string.")
    req(
        (e.target_entity_type is None) == (e.target_entity_id is None),
        "targetEntityType and targetEntityId must be specified together.",
    )
    req(
        not (e.event == "$unset" and e.properties.is_empty),
        "properties cannot be empty for $unset event",
    )
    req(
        not is_reserved_prefix(e.event) or is_special_event(e.event),
        f"{e.event} is not a supported reserved event name.",
    )
    req(
        not is_special_event(e.event)
        or (e.target_entity_type is None and e.target_entity_id is None),
        f"Reserved event {e.event} cannot have targetEntity",
    )
    req(
        not is_reserved_prefix(e.entity_type)
        or e.entity_type in BUILTIN_ENTITY_TYPES,
        f"The entityType {e.entity_type} is not allowed. "
        "'pio_' is a reserved name prefix.",
    )
    if e.target_entity_type is not None:
        req(
            not is_reserved_prefix(e.target_entity_type)
            or e.target_entity_type in BUILTIN_ENTITY_TYPES,
            f"The targetEntityType {e.target_entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.",
        )
    for k in e.properties.keySet():
        req(
            not is_reserved_prefix(k) or k in BUILTIN_PROPERTIES,
            f"The property {k} is not allowed. 'pio_' is a reserved name prefix.",
        )
    _req_json_numbers(e.properties.fields)


def _req_json_numbers(v: Any) -> None:
    """NaN/Infinity are not JSON; json.loads accepts them as an extension
    but letting them into the store would fail at serialization time (and
    poison sqlite json_extract scans) — reject at validation instead so
    the API returns 400, not a 500 deep in the insert path."""
    if isinstance(v, float):
        if v != v or v in (float("inf"), float("-inf")):
            raise EventValidationError(
                f"property values must be JSON numbers; got {v!r}")
    elif isinstance(v, dict):
        for x in v.values():
            _req_json_numbers(x)
    elif isinstance(v, (list, tuple)):
        for x in v:
            _req_json_numbers(x)


def new_event_id() -> str:
    """Opaque unique event ID (replaces HBase rowkey uuid-low, HBEventsUtil.scala:81-129)."""
    return uuid.uuid4().hex
