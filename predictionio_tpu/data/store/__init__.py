"""Name-based, engine-facing event store facades.

Parity targets: ``PEventStore`` (``data/.../store/PEventStore.scala:30-116``),
``LEventStore`` (``store/LEventStore.scala:30-142``), and
``Common.appNameToId`` (``store/Common.scala:28-49``) which resolves
(appName, channelName) -> (appId, channelId) via the metadata repositories.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.data import storage
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import UNSET


def app_name_to_id(app_name: str,
                   channel_name: Optional[str] = None) -> Tuple[int, Optional[int]]:
    """(appName, channelName) -> (appId, channelId); raises on unknown names
    (Common.scala:28-49)."""
    apps = storage.get_metadata_apps()
    app = apps.get_by_name(app_name)
    if app is None:
        raise ValueError(
            f"App name {app_name} is not found. Have you created this app?")
    channel_id: Optional[int] = None
    if channel_name is not None:
        channels = storage.get_metadata_channels().get_by_appid(app.id)
        match = next((c for c in channels if c.name == channel_name), None)
        if match is None:
            raise ValueError(
                f"Channel name {channel_name} is not found for app {app_name}.")
        channel_id = match.id
    return app.id, channel_id


class PEventStore:
    """Bulk reads for training (PEventStore.scala:54,94)."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
    ) -> List[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return storage.get_pevents().find(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id)

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return storage.get_pevents().aggregate_properties(
            app_id=app_id, entity_type=entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)

    @staticmethod
    def find_columnar(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        value_property: Optional[str] = None,
        default_value: float = 1.0,
        strict: bool = True,
    ):
        """Struct-of-arrays bulk read — the TPU ingest path (no reference
        analog; replaces RDD[Event] + per-template reshaping with one
        vectorized scan, see data/columnar.py)."""
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return storage.get_pevents().find_columnar(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names, target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict)


class LEventStore:
    """Low-latency reads at predict time (LEventStore.scala:58,114).

    The reference exposes blocking calls with a timeout; our sqlite/memory
    backends are local so calls are direct.
    """

    @staticmethod
    def find_by_entity(
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        limit: Optional[int] = None,
        latest: bool = True,
    ) -> List[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return list(storage.get_levents().find(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id, limit=limit, reversed=latest))

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
    ) -> List[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return list(storage.get_levents().find(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id, limit=limit))
