"""Name-based, engine-facing event store facades.

Parity targets: ``PEventStore`` (``data/.../store/PEventStore.scala:30-116``),
``LEventStore`` (``store/LEventStore.scala:30-142``), and
``Common.appNameToId`` (``store/Common.scala:28-49``) which resolves
(appName, channelName) -> (appId, channelId) via the metadata repositories.
"""

from __future__ import annotations

import datetime as _dt
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.data import storage
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import UNSET


def app_name_to_id(app_name: str,
                   channel_name: Optional[str] = None) -> Tuple[int, Optional[int]]:
    """(appName, channelName) -> (appId, channelId); raises on unknown names
    (Common.scala:28-49)."""
    apps = storage.get_metadata_apps()
    app = apps.get_by_name(app_name)
    if app is None:
        raise ValueError(
            f"App name {app_name} is not found. Have you created this app?")
    channel_id: Optional[int] = None
    if channel_name is not None:
        channels = storage.get_metadata_channels().get_by_appid(app.id)
        match = next((c for c in channels if c.name == channel_name), None)
        if match is None:
            raise ValueError(
                f"Channel name {channel_name} is not found for app {app_name}.")
        channel_id = match.id
    return app.id, channel_id


class PEventStore:
    """Bulk reads for training (PEventStore.scala:54,94)."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
    ) -> List[Event]:
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return storage.get_pevents().find(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id)

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Current entity-property state for training reads.

        The unbounded call (no ``start_time``/``until_time``) is served
        from the backend's MATERIALIZED aggregate — O(current entities),
        not O(event history); bounded calls replay (see
        ``LEvents.aggregate_properties``)."""
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return storage.get_pevents().aggregate_properties(
            app_id=app_id, entity_type=entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)

    @staticmethod
    def find_columnar(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        value_property: Optional[str] = None,
        default_value: float = 1.0,
        strict: bool = True,
    ):
        """Struct-of-arrays bulk read — the TPU ingest path (no reference
        analog; replaces RDD[Event] + per-template reshaping with one
        vectorized scan, see data/columnar.py)."""
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return storage.get_pevents().find_columnar(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names, target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict)

    @staticmethod
    def find_columnar_blocks(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        value_property: Optional[str] = None,
        default_value: float = 1.0,
        strict: bool = True,
        block_size: int = 1_000_000,
        prefetch: int = 0,
    ):
        """Streaming bulk read: ColumnarEvents blocks in storage order —
        the ≥10M-event ingest path (partitioned reads like
        JDBCPEvents.scala:31-100 / HBPEvents.scala:83-89; backends bound
        per-block memory). ``prefetch`` hints how far the backend may
        read/decode ahead (jsonlfs: that many partitions in parallel);
        backends without a natural unit ignore it."""
        app_id, channel_id = app_name_to_id(app_name, channel_name)
        return storage.get_pevents().find_columnar_blocks(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names, target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict, block_size=block_size, prefetch=prefetch)


class LEventStoreTimeoutError(TimeoutError):
    """Predict-time read exceeded its deadline (the reference's
    TimeoutException from Await.result, LEventStore.scala:58)."""


class _DaemonReadPool:
    """Minimal worker pool with DAEMON threads for deadline-bounded reads.

    ``concurrent.futures.ThreadPoolExecutor`` joins its (non-daemon)
    workers at interpreter exit — a permanently wedged read (exactly the
    scenario the pool guards against) would hang process shutdown.
    Daemon workers match every other background thread in the codebase.
    """

    def __init__(self, max_workers: int = 16):
        import queue

        self._tasks: "queue.Queue" = queue.Queue()
        self._max_workers = max_workers
        self._spawned = 0
        self._lock = threading.Lock()

    def _worker(self) -> None:
        while True:
            fn, box, done, started = self._tasks.get()
            started.set()
            try:
                box.append((True, fn()))
            except BaseException as e:  # delivered to the waiter
                box.append((False, e))
            finally:
                done.set()

    def submit(self, fn):
        with self._lock:
            # grow lazily up to the cap (a wedged worker never returns,
            # so permanently losing threads to wedged reads is bounded)
            if self._spawned < self._max_workers:
                self._spawned += 1
                t = threading.Thread(target=self._worker, daemon=True,
                                     name=f"pio-leventstore-{self._spawned}")
                t.start()
        box: list = []
        done = threading.Event()
        started = threading.Event()
        self._tasks.put((fn, box, done, started))
        return box, done, started


_read_pool = None
_read_pool_lock = threading.Lock()


def _pool() -> _DaemonReadPool:
    global _read_pool
    with _read_pool_lock:
        if _read_pool is None:
            _read_pool = _DaemonReadPool()
        return _read_pool


# (DAO instance, its breaker): re-resolved only when storage.reset()
# swaps the DAO — predict-time reads must not pay the process-global
# breaker-registry lock per call
_breaker_cache: Tuple[Any, Any] = (None, None)


def _event_store_breaker():
    """The circuit breaker guarding the EVENTDATA backend this process
    reads at predict time (None when storage is not resolvable)."""
    global _breaker_cache
    from predictionio_tpu.utils import resilience

    try:
        le = storage.get_levents()
    except Exception:
        return None
    cached_le, cached_br = _breaker_cache
    if cached_le is le:
        return cached_br
    ep = resilience.endpoint_of(le)
    br = resilience.breaker_for(ep) if ep else None
    _breaker_cache = (le, br)
    return br


def _bounded(fn, timeout: Optional[float]):
    """Run ``fn`` with an optional deadline (seconds). ``None`` = direct
    call (no extra thread hop on the common local-backend path). The
    deadline path hops to a pool thread, which would otherwise lose the
    caller's request-id/trace contextvars — exactly where slow-read
    attribution matters most — so the snapshot rides along.

    Resilience wiring: when the event store's circuit breaker is open,
    the read fails IMMEDIATELY (no pool hop, no timeout wait — a
    blacked-out store must cost a query microseconds, not its full
    deadline). Every failure marks the active
    :func:`~predictionio_tpu.utils.resilience.degraded_scope` before
    propagating, so templates that swallow the error and serve from the
    device factor store still get the response stamped ``degraded``."""
    from predictionio_tpu.utils import resilience

    # the kill switch bypasses the breaker HERE too (consulting or
    # feeding it while disabled would let state accumulate invisibly)
    br = _event_store_breaker() if resilience.enabled() else None
    if br is not None and br.is_blocking:
        from predictionio_tpu.data.storage.base import StorageCircuitOpen

        resilience.mark_degraded("circuit_open")
        raise StorageCircuitOpen(br.endpoint, br.retry_in)
    try:
        if timeout is None:
            return fn()
        from predictionio_tpu.utils.tracing import carrying_context

        box, done, started = _pool().submit(carrying_context(fn))
        if not done.wait(timeout):
            err = LEventStoreTimeoutError(
                f"event-store read exceeded {timeout}s")
            if br is not None and started.is_set():
                # a HUNG store never raises inside the DAO (where op
                # failures are normally counted) — the deadline here is
                # the only layer that sees it, and without this a
                # wedged backend would cost every query its full read
                # timeout instead of tripping the fast-fail breaker.
                # A task still QUEUED behind busy workers says nothing
                # about the store: counting client-side congestion as
                # endpoint failures would open the breaker (and flip
                # every replica's /healthz) on a healthy backend.
                br.record_failure(err)
            raise err
        ok, value = box[0]
        if ok:
            return value
        raise value
    except BaseException as e:
        resilience.mark_degraded(resilience.degrade_reason_for(e))
        raise


class LEventStore:
    """Low-latency reads at predict time (LEventStore.scala:58,114).

    The reference's calls block with a ``timeout: Duration``; here
    ``timeout`` (seconds) bounds the read the same way — predict-time
    constraint lookups are on the serving hot path, and a wedged backend
    must surface as a fast ``LEventStoreTimeoutError`` (which templates
    catch and degrade on), not a stalled query. ``None`` runs direct.
    """

    @staticmethod
    def find_by_entity(
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        limit: Optional[int] = None,
        latest: bool = True,
        timeout: Optional[float] = None,
    ) -> List[Event]:
        def read():
            # the metadata lookup hits the same backend — it must run
            # under the deadline too, or a wedged store stalls the caller
            # before _bounded is ever reached
            app_id, channel_id = app_name_to_id(app_name, channel_name)
            return list(storage.get_levents().find(
                app_id=app_id, channel_id=channel_id, start_time=start_time,
                until_time=until_time, entity_type=entity_type,
                entity_id=entity_id, event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id, limit=limit,
                reversed=latest))

        return _bounded(read, timeout)

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> List[Event]:
        def read():
            # metadata lookup under the deadline too (see find_by_entity)
            app_id, channel_id = app_name_to_id(app_name, channel_name)
            return list(storage.get_levents().find(
                app_id=app_id, channel_id=channel_id, start_time=start_time,
                until_time=until_time, entity_type=entity_type,
                entity_id=entity_id, event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id, limit=limit))

        return _bounded(read, timeout)
