"""Shared evaluation-split helpers: sliding time windows + leave-last-out.

Both recommendation-family templates (``templates/recommendation`` and
``templates/sequentialrec``) evaluate with the same two protocols the
reference's movielens-evaluation example defines
(``EventsSlidingEvalParams``: firstTrainingUntilTime / evalDuration /
evalCount, and the leave-last-out default). The split MATH lives here so
it is unit-testable on bare arrays — the templates only decode the
masks/holdouts into their own TrainingData shapes.

Window semantics (the boundary contract the tests pin):

- window ``k`` trains on events strictly BEFORE ``t0 + k*duration``;
- it tests on events in ``[t0 + k*duration, t0 + (k+1)*duration)`` —
  an event exactly AT a cut belongs to that cut's TEST window and to
  every LATER window's training set.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence, Tuple, TypeVar

import numpy as np

K = TypeVar("K")
V = TypeVar("V")


def sliding_window_masks(times: np.ndarray, t0: float, duration: float,
                         count: int,
                         hint: str = "move the first cut later or "
                                     "reduce the window count"
                         ) -> Iterator[
                             Tuple[int, np.ndarray, np.ndarray]]:
    """Yield ``(k, train_mask, test_mask)`` per sliding window.

    ``times`` is float64 epoch seconds aligned with whatever row set the
    caller slices; ``t0`` the first cut; ``duration`` the window length
    in seconds. A window with NO training events raises — training on
    an empty set would crash deeper in with a far worse message.
    ``hint`` lets the caller name ITS configuration flags in the error
    (the templates pass "move eval_first_until later or reduce
    eval_count" so operators see the knobs they actually set).
    """
    times = np.asarray(times, dtype=np.float64)
    if duration <= 0:
        raise ValueError(
            f"sliding-eval window duration must be positive, got "
            f"{duration}")
    for k in range(int(count)):
        cut = t0 + k * duration
        train_mask = times < cut
        if not train_mask.any():
            raise ValueError(
                f"sliding-eval window {k} has no training events before "
                f"its cut — {hint}")
        test_mask = (times >= cut) & (times < cut + duration)
        yield k, train_mask, test_mask


def leave_last_out(groups: Dict[K, List[V]]) \
        -> Tuple[List[V], List[Tuple[K, V]]]:
    """Per-group leave-last-out split over ALREADY-ORDERED groups.

    ``groups`` maps key -> its events in evaluation order (stream or
    time order — the caller's choice is the protocol). Groups with
    fewer than 2 events go whole into training (no holdout: a
    single-event user cannot both train and test). Returns
    ``(train_events, [(key, held_out_last_event), ...])`` preserving
    each group's internal order and the dict's group order.
    """
    train: List[V] = []
    held: List[Tuple[K, V]] = []
    for key, rs in groups.items():
        if len(rs) < 2:
            train.extend(rs)
            continue
        train.extend(rs[:-1])
        held.append((key, rs[-1]))
    return train, held


def ndcg_at_k(ranked: Sequence, relevant, k: int) -> float:
    """Binary-relevance NDCG@k of one ranked list (the sequence-aware
    metric next to Precision@k — rank position matters, so a model
    that puts the held-out next item FIRST beats one that buries it at
    position k, which Precision@k cannot distinguish).

    ``ranked`` is the recommendation list best-first; ``relevant`` the
    held-out item collection (set semantics). DCG uses the standard
    ``1/log2(rank+1)`` gain; the ideal DCG places all |relevant| items
    (clipped to k) on top. Empty ``relevant`` returns 0.0 — callers
    following OptionAverageMetric semantics should skip those instead.
    """
    rel = set(relevant)
    if not rel:
        return 0.0
    k = int(k)
    dcg = 0.0
    for pos, item in enumerate(ranked[:k]):
        if item in rel:
            dcg += 1.0 / np.log2(pos + 2.0)
    ideal = sum(1.0 / np.log2(pos + 2.0)
                for pos in range(min(k, len(rel))))
    return float(dcg / ideal)


def group_by_entity(entities: Sequence, payloads: Sequence[V]) \
        -> Dict[str, List[V]]:
    """Group aligned (entity, payload) rows into an insertion-ordered
    dict of per-entity payload lists — the shared precursor of
    :func:`leave_last_out`."""
    groups: Dict[str, List[V]] = {}
    for ent, payload in zip(entities, payloads):
        groups.setdefault(str(ent), []).append(payload)
    return groups


__all__ = ["sliding_window_masks", "leave_last_out", "group_by_entity",
           "ndcg_at_k"]
