"""Bidirectional ID mapping for dense matrix indexing.

Parity target: reference ``data/.../storage/BiMap.scala:63-129`` — every ALS
template uses ``BiMap.stringInt`` to map entity IDs onto matrix rows.

TPU-native design: the forward map is a plain dict; the inverse is an
O(1) numpy object array so that batched index->ID decoding of model output
(top-k recommendation lists) is vectorized host-side.
"""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, Iterator, List, Optional, Sequence

import numpy as np


class BiMap:
    """Immutable bidirectional map K <-> V (unique values required)."""

    def __init__(self, forward: Dict[Hashable, Hashable]):
        self._fwd = dict(forward)
        self._inv: Optional[Dict[Hashable, Hashable]] = None
        if len(set(self._fwd.values())) != len(self._fwd):
            raise ValueError("BiMap values must be unique")

    # -- constructors (BiMap.scala:63-129) --------------------------------
    @classmethod
    def string_int(cls, keys: Iterable[str]) -> "StringIndexBiMap":
        """Map distinct keys to dense ints 0..n-1, insertion-ordered."""
        return StringIndexBiMap(keys)

    string_long = string_int  # Python ints are unbounded; same thing

    # -- access ------------------------------------------------------------
    def __getitem__(self, k: Hashable) -> Hashable:
        return self._fwd[k]

    def get(self, k: Hashable, default=None):
        return self._fwd.get(k, default)

    def __contains__(self, k: Hashable) -> bool:
        return k in self._fwd

    def __len__(self) -> int:
        return len(self._fwd)

    def __iter__(self) -> Iterator[Hashable]:
        return iter(self._fwd)

    def keys(self):
        return self._fwd.keys()

    def values(self):
        return self._fwd.values()

    def items(self):
        return self._fwd.items()

    def inverse(self) -> "BiMap":
        return BiMap({v: k for k, v in self._fwd.items()})

    def inv_get(self, v: Hashable, default=None):
        if self._inv is None:
            self._inv = {val: k for k, val in self._fwd.items()}
        return self._inv.get(v, default)

    def to_dict(self) -> Dict[Hashable, Hashable]:
        return dict(self._fwd)


class StringIndexBiMap(BiMap):
    """String -> dense int index with vectorized inverse decoding."""

    def __init__(self, keys: Iterable[str]):
        ordered: List[str] = []
        seen = set()
        for k in keys:
            if k not in seen:
                seen.add(k)
                ordered.append(k)
        super().__init__({k: i for i, k in enumerate(ordered)})
        self._labels = np.asarray(ordered, dtype=object)

    @classmethod
    def from_distinct(cls, labels: Sequence[str]) -> "StringIndexBiMap":
        """Build from already-distinct labels without re-deduplicating —
        the vectorized path used by ColumnarEvents.encode_entities, where
        ``np.unique`` has produced the distinct set already."""
        self = cls.__new__(cls)
        BiMap.__init__(self, {str(k): i for i, k in enumerate(labels)})
        self._labels = np.asarray([str(k) for k in labels], dtype=object)
        return self

    @property
    def labels(self) -> np.ndarray:
        """Object ndarray such that labels[i] == key with index i."""
        return self._labels

    def append(self, labels: Sequence[str]) -> List[int]:
        """Extend the map with NEW labels in place, assigning the next
        dense indices; returns their indices. Labels already present are
        an error — the caller (online fold-in growing the user universe
        under a live server) resolves known ids first. Publish order
        matters for lock-free readers: the factor store must be patched
        BEFORE the labels land here, so a predict-path ``get`` never
        resolves an index the store does not hold yet."""
        new = [str(k) for k in labels]
        if len(set(new)) != len(new):
            # an intra-batch duplicate would pass the per-label check
            # below (neither copy is mapped yet) and then permanently
            # misalign _fwd and _labels — one fwd entry, two label rows
            raise ValueError("append: duplicate labels within the batch")
        for k in new:
            if k in self._fwd:
                raise ValueError(f"label {k!r} already mapped")
        base = len(self._fwd)
        out = []
        for i, k in enumerate(new):
            self._fwd[k] = base + i
            out.append(base + i)
        if new:
            self._labels = np.concatenate(
                [self._labels, np.asarray(new, dtype=object)])
            self._inv = None  # lazy inverse rebuilt on next inv_get
        return out

    def decode(self, indices) -> np.ndarray:
        """Vectorized index->key decoding (for top-k model outputs)."""
        return self._labels[np.asarray(indices)]

    def encode(self, keys: Sequence[str]) -> np.ndarray:
        """Vectorized key->index encoding; raises KeyError on unknowns."""
        try:
            return np.fromiter((self._fwd[k] for k in keys), dtype=np.int32,
                               count=len(keys))
        except KeyError as e:
            raise KeyError(f"unknown key {e.args[0]!r} in BiMap.encode") from e
