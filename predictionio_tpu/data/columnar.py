"""Columnar event batches — the TPU ingest format.

The reference's training reads return ``RDD[Event]`` (``PEvents.scala:77-86``)
and every template immediately re-shapes them into numeric triples for MLlib
(``examples/scala-parallel-recommendation/custom-query/src/main/scala/
DataSource.scala:31-65``). On a TPU host that per-row object path is the
ingest bottleneck (SURVEY hard part #2), so the data plane's canonical bulk
read is a struct-of-arrays batch instead: entity/target IDs as numpy object
arrays, one extracted numeric property column, and event times — everything
downstream (BiMap indexing, padding, ``jax.device_put``) is vectorized.

Backends may build these straight from their native scan (see
``SqlitePEvents.find_columnar`` which extracts the value column inside SQL);
``events_to_columnar`` is the generic fallback and also the conformance
oracle the backend fast paths are tested against.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import numpy as np

from predictionio_tpu.data.event import Event


@dataclasses.dataclass
class ColumnarEvents:
    """Struct-of-arrays view of an event scan, aligned by row.

    ``entity_ids``/``target_ids`` are object arrays (``target_ids`` entries
    may be None for events without a target); ``values`` is the extracted
    numeric property (``default_value`` where absent or non-numeric);
    ``event_times`` is float64 epoch seconds (UTC).
    """

    entity_ids: np.ndarray   # object [n]
    target_ids: np.ndarray   # object [n]
    values: np.ndarray       # float32 [n]
    event_times: np.ndarray  # float64 [n] epoch seconds
    events: Optional[np.ndarray] = None  # object [n] event names (optional)

    def __len__(self) -> int:
        return int(self.entity_ids.shape[0])

    def encode_entities(self):
        """Vectorized dense indexing of both ID columns.

        Returns ``(user_map, item_map, rows, cols)`` where the maps are
        :class:`~predictionio_tpu.data.bimap.StringIndexBiMap` over the
        distinct IDs (sorted) and ``rows``/``cols`` are int64 dense codes —
        the BiMap.stringInt step of every template, done with two
        ``np.unique`` calls instead of per-row dict lookups.

        Raises ``ValueError`` if any row has no target entity (a phantom
        "None" item must never get a matrix column); filter the scan by
        ``target_entity_type`` or call :meth:`drop_missing_targets` first.
        """
        from predictionio_tpu.data.bimap import StringIndexBiMap

        missing = np.fromiter((x is None for x in self.target_ids),
                              dtype=bool, count=len(self.target_ids))
        if missing.any():
            raise ValueError(
                f"{int(missing.sum())} events have no target entity; filter "
                "by target_entity_type or use drop_missing_targets() before "
                "encode_entities()")
        ent = self.entity_ids.astype(str)
        tgt = self.target_ids.astype(str)
        e_labels, rows = np.unique(ent, return_inverse=True)
        t_labels, cols = np.unique(tgt, return_inverse=True)
        return (StringIndexBiMap.from_distinct(e_labels),
                StringIndexBiMap.from_distinct(t_labels),
                rows.astype(np.int64), cols.astype(np.int64))

    def drop_missing_targets(self) -> "ColumnarEvents":
        """Rows with a target entity only (aligned across all columns)."""
        keep = np.fromiter((x is not None for x in self.target_ids),
                           dtype=bool, count=len(self.target_ids))
        return ColumnarEvents(
            entity_ids=self.entity_ids[keep],
            target_ids=self.target_ids[keep],
            values=self.values[keep],
            event_times=self.event_times[keep],
            events=None if self.events is None else self.events[keep],
        )


def events_to_columnar(events: Iterable[Event],
                       value_property: Optional[str] = None,
                       default_value: float = 1.0,
                       strict: bool = True) -> ColumnarEvents:
    """Generic Event-objects -> columnar conversion (backend fallback).

    ``value_property`` names the DataMap field to extract as the value
    column (e.g. ``"rating"``); rows without it (or with JSON null) get
    ``default_value`` — the template convention where a ``view`` event
    counts as an implicit 1.0 (``DataSource.scala:44-56``). A present but
    non-numeric value (string, bool, list, ...) raises ``ValueError`` when
    ``strict`` (matching ``DataMap.get(name, float)``'s loud failure);
    ``strict=False`` maps it to ``default_value``.
    """
    ents, tgts, vals, times, names = [], [], [], [], []
    for e in events:
        ents.append(e.entity_id)
        tgts.append(e.target_entity_id)
        times.append(e.event_time.timestamp())
        names.append(e.event)
        v = default_value
        if value_property is not None and value_property in e.properties:
            raw = e.properties[value_property]
            if isinstance(raw, (int, float)) and not isinstance(raw, bool):
                v = float(raw)
            elif raw is not None and strict:
                raise ValueError(
                    f"property {value_property!r} of event "
                    f"{e.event_id or e.event!r} is non-numeric: {raw!r}")
        vals.append(v)
    n = len(ents)
    return ColumnarEvents(
        entity_ids=np.asarray(ents, dtype=object) if n
        else np.empty(0, dtype=object),
        target_ids=np.asarray(tgts, dtype=object) if n
        else np.empty(0, dtype=object),
        values=np.asarray(vals, dtype=np.float32),
        event_times=np.asarray(times, dtype=np.float64),
        events=np.asarray(names, dtype=object) if n
        else np.empty(0, dtype=object),
    )
