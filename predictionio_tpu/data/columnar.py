"""Columnar event batches — the TPU ingest format.

The reference's training reads return ``RDD[Event]`` (``PEvents.scala:77-86``)
and every template immediately re-shapes them into numeric triples for MLlib
(``examples/scala-parallel-recommendation/custom-query/src/main/scala/
DataSource.scala:31-65``). On a TPU host that per-row object path is the
ingest bottleneck (SURVEY hard part #2), so the data plane's canonical bulk
read is a struct-of-arrays batch instead: entity/target IDs as numpy object
arrays, one extracted numeric property column, and event times — everything
downstream (BiMap indexing, padding, ``jax.device_put``) is vectorized.

Backends may build these straight from their native scan (see
``SqlitePEvents.find_columnar`` which extracts the value column inside SQL);
``events_to_columnar`` is the generic fallback and also the conformance
oracle the backend fast paths are tested against.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import numpy as np

from predictionio_tpu.data.event import Event


@dataclasses.dataclass
class ColumnarEvents:
    """Struct-of-arrays view of an event scan, aligned by row.

    ``entity_ids``/``target_ids`` are object arrays (``target_ids`` entries
    may be None for events without a target); ``values`` is the extracted
    numeric property (``default_value`` where absent or non-numeric);
    ``event_times`` is float64 epoch seconds (UTC).

    DICTIONARY-ENCODED blocks (the 10M+-event ingest fast lane from the
    native codec): the ``*_codes``/``*_labels`` fields carry int32 codes
    into small distinct-label tables and the object columns are None —
    only distinct values ever become Python strings. Call
    :meth:`materialize` for the object-array form;
    :class:`StreamingRatingsBuilder` consumes the codes directly. A code
    of -1 means absent (None target).
    """

    entity_ids: Optional[np.ndarray]   # object [n] (None when encoded)
    target_ids: Optional[np.ndarray]   # object [n] (None when encoded)
    values: np.ndarray       # float32 [n]
    event_times: np.ndarray  # float64 [n] epoch seconds
    events: Optional[np.ndarray] = None  # object [n] event names (optional)
    entity_codes: Optional[np.ndarray] = None   # int32 [n]
    entity_labels: Optional[np.ndarray] = None  # object [k] distinct
    target_codes: Optional[np.ndarray] = None
    target_labels: Optional[np.ndarray] = None
    event_codes: Optional[np.ndarray] = None
    event_labels: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return int(self.values.shape[0])

    @property
    def is_encoded(self) -> bool:
        return self.entity_codes is not None

    def materialize(self) -> "ColumnarEvents":
        """Encoded block -> object-array block (labels gathered by code;
        -1 target codes become None)."""
        if not self.is_encoded:
            return self

        def decode(codes, labels, none_for_missing):
            out = np.empty(len(codes), dtype=object)
            present = codes >= 0
            out[present] = labels[codes[present]]
            if none_for_missing:
                out[~present] = None
            return out

        return ColumnarEvents(
            entity_ids=decode(self.entity_codes, self.entity_labels,
                              False),
            target_ids=decode(self.target_codes, self.target_labels,
                              True)
            if self.target_codes is not None else self.target_ids,
            values=self.values,
            event_times=self.event_times,
            events=decode(self.event_codes, self.event_labels, False)
            if self.event_codes is not None else self.events,
        )

    def encode_entities(self):
        """Vectorized dense indexing of both ID columns.

        Returns ``(user_map, item_map, rows, cols)`` where the maps are
        :class:`~predictionio_tpu.data.bimap.StringIndexBiMap` over the
        distinct IDs (sorted) and ``rows``/``cols`` are int64 dense codes —
        the BiMap.stringInt step of every template, done with two
        ``np.unique`` calls instead of per-row dict lookups.

        Raises ``ValueError`` if any row has no target entity (a phantom
        "None" item must never get a matrix column); filter the scan by
        ``target_entity_type`` or call :meth:`drop_missing_targets` first.
        """
        from predictionio_tpu.data.bimap import StringIndexBiMap

        if self.is_encoded:
            return self.materialize().encode_entities()
        missing = np.fromiter((x is None for x in self.target_ids),
                              dtype=bool, count=len(self.target_ids))
        if missing.any():
            raise ValueError(
                f"{int(missing.sum())} events have no target entity; filter "
                "by target_entity_type or use drop_missing_targets() before "
                "encode_entities()")
        ent = self.entity_ids.astype(str)
        tgt = self.target_ids.astype(str)
        e_labels, rows = np.unique(ent, return_inverse=True)
        t_labels, cols = np.unique(tgt, return_inverse=True)
        return (StringIndexBiMap.from_distinct(e_labels),
                StringIndexBiMap.from_distinct(t_labels),
                rows.astype(np.int64), cols.astype(np.int64))

    def drop_missing_targets(self) -> "ColumnarEvents":
        """Rows with a target entity only (aligned across all columns)."""
        if self.is_encoded and self.target_codes is not None:
            return self.take(self.target_codes >= 0)
        keep = np.fromiter((x is not None for x in self.target_ids),
                           dtype=bool, count=len(self.target_ids))
        return self.take(keep)

    def take(self, index) -> "ColumnarEvents":
        """Aligned row selection (boolean mask, index array, or slice)."""
        def sl(a):
            return None if a is None else a[index]

        return ColumnarEvents(
            entity_ids=sl(self.entity_ids),
            target_ids=sl(self.target_ids),
            values=self.values[index],
            event_times=self.event_times[index],
            events=sl(self.events),
            entity_codes=sl(self.entity_codes),
            entity_labels=self.entity_labels,
            target_codes=sl(self.target_codes),
            target_labels=self.target_labels,
            event_codes=sl(self.event_codes),
            event_labels=self.event_labels,
        )

    @staticmethod
    def concat(batches: "list[ColumnarEvents]") -> "ColumnarEvents":
        """Row-wise concatenation in object-array form (encoded inputs
        are materialized first — label tables differ across blocks);
        events column kept only if every batch has one."""
        batches = [b.materialize() for b in batches]
        if not batches:
            return ColumnarEvents(
                entity_ids=np.empty(0, dtype=object),
                target_ids=np.empty(0, dtype=object),
                values=np.empty(0, dtype=np.float32),
                event_times=np.empty(0, dtype=np.float64),
                events=np.empty(0, dtype=object))
        has_events = all(b.events is not None for b in batches)
        return ColumnarEvents(
            entity_ids=np.concatenate([b.entity_ids for b in batches]),
            target_ids=np.concatenate([b.target_ids for b in batches]),
            values=np.concatenate([b.values for b in batches]),
            event_times=np.concatenate([b.event_times for b in batches]),
            events=np.concatenate([b.events for b in batches])
            if has_events else None,
        )


def _unique_codes(codes: np.ndarray, n_labels: int):
    """``np.unique(codes, return_inverse=True)`` for NON-NEGATIVE codes
    bounded by a (small) label-table size: O(n + k) presence scan +
    table lookup instead of an O(n log n) sort — the ingest consumer's
    hottest per-block step. Same contract: sorted distinct codes, and
    the inverse mapping into them."""
    present = np.zeros(n_labels, dtype=bool)
    present[codes] = True
    uniq = np.flatnonzero(present)
    remap = np.empty(n_labels, dtype=np.int64)
    remap[uniq] = np.arange(len(uniq))
    return uniq, remap[codes]


class StreamingRatingsBuilder:
    """Incremental (user, item, value) triple builder over columnar
    blocks — the ≥10M-rating ingest core (SURVEY hard part #2).

    Feeding blocks from ``find_columnar_blocks`` keeps peak memory at
    one block of object-dtype IDs plus the accumulated INTEGER triples
    (16 bytes/rating) — per-event Python objects and whole-store string
    columns never exist. ID indexing is the BiMap.stringInt step done
    incrementally: one ``np.unique`` per block plus dictionary inserts
    per NEW distinct entity (distinct users/items are orders of
    magnitude fewer than events at MovieLens-20M scale).
    """

    def __init__(self):
        self._users: dict = {}
        self._items: dict = {}
        self._rows: List[np.ndarray] = []
        self._cols: List[np.ndarray] = []
        self._vals: List[np.ndarray] = []
        self.n_events = 0

    def _encode(self, ids: np.ndarray, table: dict) -> np.ndarray:
        labels, inv = np.unique(ids.astype(str), return_inverse=True)
        return self._merge_labels(labels, table)[inv]

    def _merge_labels(self, labels: np.ndarray, table: dict) -> np.ndarray:
        """Block-local distinct labels -> global codes (the only per-item
        Python work on the encoded path)."""
        out = np.empty(len(labels), dtype=np.int64)
        for j, lab in enumerate(labels):
            code = table.get(lab)
            if code is None:
                code = len(table)
                table[lab] = code
            out[j] = code
        return out

    def add_block(self, block: ColumnarEvents) -> None:
        if not len(block):
            return
        if block.is_encoded:
            # dictionary-encoded block (native-codec fast lane): remap
            # the block's small label tables into the global dicts and
            # gather — zero per-event Python objects. Only labels a KEPT
            # row actually references are registered: a part's label
            # table spans the whole file, and upstream filters must not
            # leak phantom entities into the maps.
            ecodes = block.entity_codes
            tcodes = block.target_codes
            if (ecodes < 0).any():
                raise ValueError(
                    f"{int((ecodes < 0).sum())} events have no entity id; "
                    "filter the scan (e.g. by entity_type) before "
                    "streaming ingest")
            keep = tcodes >= 0
            if not keep.all():
                ecodes, tcodes = ecodes[keep], tcodes[keep]
                vals = np.asarray(block.values, dtype=np.float32)[keep]
            else:
                vals = np.asarray(block.values, dtype=np.float32)
            if not len(ecodes):
                return
            uniq_e, inv_e = _unique_codes(ecodes,
                                          len(block.entity_labels))
            uniq_t, inv_t = _unique_codes(tcodes,
                                          len(block.target_labels))
            self._rows.append(self._merge_labels(
                block.entity_labels[uniq_e], self._users)[inv_e])
            self._cols.append(self._merge_labels(
                block.target_labels[uniq_t], self._items)[inv_t])
            self._vals.append(vals)
            self.n_events += len(ecodes)
            return
        # same guard as TrainingData/encode_entities: a None entity id
        # must never become the literal string "None" and train a
        # phantom row — the streaming path may not silently diverge
        bad = np.fromiter((x is None for x in block.entity_ids),
                          dtype=bool, count=len(block.entity_ids))
        if bad.any():
            raise ValueError(
                f"{int(bad.sum())} events have no entity id; filter the "
                "scan (e.g. by entity_type) before streaming ingest")
        missing = np.fromiter((x is None for x in block.target_ids),
                              dtype=bool, count=len(block.target_ids))
        if missing.any():
            block = block.take(~missing)
            if not len(block):
                return
        self._rows.append(self._encode(block.entity_ids, self._users))
        self._cols.append(self._encode(block.target_ids, self._items))
        self._vals.append(np.asarray(block.values, dtype=np.float32))
        self.n_events += len(block)

    def finalize(self):
        """-> (user_map, item_map, rows, cols, values) with dense int64
        codes in first-seen order."""
        from predictionio_tpu.data.bimap import StringIndexBiMap

        user_map = StringIndexBiMap.from_distinct(list(self._users))
        item_map = StringIndexBiMap.from_distinct(list(self._items))
        rows = (np.concatenate(self._rows) if self._rows
                else np.empty(0, dtype=np.int64))
        cols = (np.concatenate(self._cols) if self._cols
                else np.empty(0, dtype=np.int64))
        vals = (np.concatenate(self._vals) if self._vals
                else np.empty(0, dtype=np.float32))
        return user_map, item_map, rows, cols, vals


class PipelinedRatingsBuilder(StreamingRatingsBuilder):
    """StreamingRatingsBuilder whose consumer stage also PRE-SORTS each
    block's triples by their packed (row, col) key as blocks arrive —
    the per-block share of the dedup sort, done inside the
    decode/index overlap window. :meth:`finalize_bucketed` then
    replaces the monolithic O(N log N) argsort over the full COO
    arrays with a stable O(N log k) k-way merge of the already-sorted
    runs (native kernel, GIL released) and feeds both solve sides'
    bucket scatter + async H2D staging from it.

    Byte-identity with the serial path is by construction: the merge
    permutation equals ``np.argsort(key, kind="stable")`` over the
    stream-ordered triples (per-block stable sorts + stable merge keep
    every duplicate pair's stream order), and the dedup summation and
    bucket scatter are the very same code the serial
    ``bucket_ratings_pair`` runs.

    Note :meth:`finalize` (the uniform-path contract) returns triples
    in merged (row, col) order rather than stream order — the same
    multiset, and identical training inputs for every consumer that
    dedups (pad_ratings / bucket_ratings_pair both do). A consumer
    that is sensitive to raw triple ORDER (e.g. a leave-last-out eval
    split) must use :class:`StreamingRatingsBuilder` instead."""

    def add_block(self, block: ColumnarEvents) -> None:
        runs_before = len(self._rows)
        super().add_block(block)
        if len(self._rows) == runs_before:
            return  # block empty or fully filtered
        r, c = self._rows[-1], self._cols[-1]
        # rows fit 31 bits at any realistic entity count; cols 32
        key = (r << np.int64(32)) | c
        order = np.argsort(key, kind="stable")
        self._rows[-1] = r[order]
        self._cols[-1] = c[order]
        self._vals[-1] = self._vals[-1][order]

    def merge_sorted(self):
        """-> (rows, cols, vals, keys) globally stable-sorted by
        (row, col): the k-way merge of the per-block sorted runs
        (``keys`` is the sorted packed key array — callers feed it to
        the dedup without re-packing). Equal keys keep stream order, so
        :func:`ops.als.dedup_sum_sorted` sums duplicates in exactly the
        serial path's order."""
        from predictionio_tpu.native import codec as _native

        if not self._rows:
            z = np.empty(0, dtype=np.int64)
            return z, z.copy(), np.empty(0, dtype=np.float32), z.copy()
        rows = np.concatenate(self._rows)
        cols = np.concatenate(self._cols)
        vals = np.concatenate(self._vals)
        keys = (rows << np.int64(32)) | cols
        if len(self._rows) > 1:
            offsets = np.zeros(len(self._rows) + 1, dtype=np.int64)
            np.cumsum([len(a) for a in self._rows], out=offsets[1:])
            perm = _native.merge_sorted_runs(keys, offsets)
            if perm is None:  # no native lib: same permutation, full sort
                perm = np.argsort(keys, kind="stable")
            rows, cols, vals, keys = \
                rows[perm], cols[perm], vals[perm], keys[perm]
        return rows, cols, vals, keys

    def finalize(self):
        """Uniform-path contract (user_map, item_map, rows, cols,
        values) — triples arrive merged-sorted, not stream-ordered."""
        from predictionio_tpu.data.bimap import StringIndexBiMap

        user_map = StringIndexBiMap.from_distinct(list(self._users))
        item_map = StringIndexBiMap.from_distinct(list(self._items))
        rows, cols, vals, _ = self.merge_sorted()
        return user_map, item_map, rows, cols, vals

    def finalize_bucketed(self, bucket_lengths=None, max_len=None,
                          pad_multiple: int = 8, row_multiple: int = 8,
                          stage_device: bool = False, device=None,
                          warmup_params=None,
                          timeline=None) -> "PipelinedIngestResult":
        """Merge + dedup + bucketize both solve sides, overlapping each
        side's async H2D transfer with the other side's host scatter
        (and, when ``warmup_params`` is given, with the bucketed
        training program's AOT compile on a background thread).

        Identical bucket layouts to
        ``ops.als.bucket_ratings_pair(rows, cols, vals, ...)`` over the
        stream-ordered triples."""
        import threading as _threading

        from predictionio_tpu.data.bimap import StringIndexBiMap
        from predictionio_tpu.ops import als as _als
        from predictionio_tpu.utils.tracing import (
            StageTimeline,
            current_trace_context,
        )

        timeline = timeline if timeline is not None else StageTimeline()
        parent = current_trace_context()
        user_map = StringIndexBiMap.from_distinct(list(self._users))
        item_map = StringIndexBiMap.from_distinct(list(self._items))
        n_u, n_i = len(user_map), len(item_map)
        with timeline.scope("merge", parent):
            rows, cols, vals, key = self.merge_sorted()
            rows, cols, vals = _als.dedup_sum_sorted(key, rows, cols,
                                                     vals)
        with timeline.scope("bucket.user", parent):
            user_side = _als._bucket_grouped(
                rows, cols, vals, n_u, n_i, bucket_lengths, max_len,
                pad_multiple, row_multiple)
        nnz = int(len(rows))
        user_host = user_side
        if stage_device:
            # user side's transfers stream WHILE the item side's
            # re-sort + scatter runs on host (double buffering)
            with timeline.scope("h2d.user.dispatch", parent):
                user_side = user_side.to_device_async(device)
        with timeline.scope("bucket.item", parent):
            o = np.argsort(cols, kind="stable")
            item_side = _als._bucket_grouped(
                cols[o], rows[o], vals[o], n_i, n_u, bucket_lengths,
                max_len, pad_multiple, row_multiple)
        item_host = item_side
        if stage_device:
            with timeline.scope("h2d.item.dispatch", parent):
                item_side = item_side.to_device_async(device)
        warmup_thread = None
        if warmup_params is not None:
            # compile hides inside the transfer window; shapes come
            # from the host-side structures so no transfer is awaited
            def _warm():
                with timeline.scope("warmup_compile", parent):
                    _als.warmup_train_als_bucketed(user_host, item_host,
                                                   warmup_params)

            warmup_thread = _threading.Thread(
                target=_warm, daemon=True, name="pio-ingest-warmup")
            warmup_thread.start()
        return PipelinedIngestResult(
            user_map=user_map, item_map=item_map, user_side=user_side,
            item_side=item_side, n_events=self.n_events, nnz=nnz,
            staged=bool(stage_device), timeline=timeline,
            _warmup_thread=warmup_thread)


@dataclasses.dataclass
class PipelinedIngestResult:
    """Everything the training step needs, plus the overlap evidence.

    ``user_side``/``item_side`` are :class:`~predictionio_tpu.ops.als.
    BucketedRatings`; with ``staged`` their tables are device arrays
    whose H2D transfers may still be in flight — call :meth:`wait`
    (idempotent) before timing-sensitive work, or just train (jax
    serializes on the data)."""

    user_map: object
    item_map: object
    user_side: object
    item_side: object
    n_events: int
    nnz: int
    staged: bool
    timeline: object
    _warmup_thread: object = None

    def wait(self, warmup: bool = True) -> "PipelinedIngestResult":
        """``warmup=False`` closes only the H2D window (ingest is
        done); the compile tail then belongs to the first training
        call — join it there via :meth:`join_warmup`."""
        from predictionio_tpu.utils.tracing import current_trace_context

        parent = current_trace_context()
        if self.staged:
            with self.timeline.scope("h2d.wait", parent):
                self.user_side.block_until_staged()
                self.item_side.block_until_staged()
        if warmup:
            self.join_warmup()
        return self

    def join_warmup(self) -> "PipelinedIngestResult":
        """Wait for the background AOT compile (no-op without one);
        train right after and the executable is already cached."""
        if self._warmup_thread is not None:
            from predictionio_tpu.utils.tracing import (
                current_trace_context,
            )

            with self.timeline.scope("warmup_wait",
                                     current_trace_context()):
                self._warmup_thread.join()
            self._warmup_thread = None
        return self


def ingest_ratings_pipelined(blocks, queue_size: int = 4,
                             bucket_lengths=None, max_len=None,
                             pad_multiple: int = 8, row_multiple: int = 8,
                             stage_device: bool = False, device=None,
                             warmup_params=None,
                             timeline=None) -> PipelinedIngestResult:
    """The overlapped ingest pipeline, end to end: drive ``blocks`` (a
    ColumnarEvents iterator, e.g. ``find_columnar_blocks``) on a
    producer thread through a bounded queue; index + block-sort each
    block on the consumer as it arrives; then merge/dedup/bucketize
    with each side's H2D transfer (and the optional training-program
    warm-up compile) overlapping the remaining host work. Returns a
    :class:`PipelinedIngestResult`; call ``.wait()`` to close the
    overlap window.

    Training inputs are byte-identical to the serial
    ``StreamingRatingsBuilder`` + ``bucket_ratings_pair`` chain — see
    :class:`PipelinedRatingsBuilder`."""
    from predictionio_tpu.utils.tracing import (
        StageTimeline,
        current_trace_context,
    )

    timeline = timeline if timeline is not None else StageTimeline()
    parent = current_trace_context()
    builder = PipelinedRatingsBuilder()
    timed_blocks = timeline.wrap_iter(blocks, "decode", parent)
    for block in iter_blocks_threaded(timed_blocks,
                                      queue_size=queue_size):
        with timeline.scope("index", parent):
            builder.add_block(block)
    return builder.finalize_bucketed(
        bucket_lengths=bucket_lengths, max_len=max_len,
        pad_multiple=pad_multiple, row_multiple=row_multiple,
        stage_device=stage_device, device=device,
        warmup_params=warmup_params, timeline=timeline)


def iter_blocks_threaded(block_iter, queue_size: int = 4):
    """Drive a block-producing iterator on a background thread, yielding
    blocks through a bounded queue — partition read + native-codec
    decode (the C++ call releases the GIL) overlap the consumer's numpy
    indexing. The bound caps in-flight memory at ``queue_size`` blocks.
    The reference gets the same overlap for free from Spark executor
    scans feeding the driver (``HBPEvents.scala:83-89``).

    Early consumer exit (an exception downstream, or the generator being
    abandoned) stops the producer promptly: the yield loop's ``finally``
    sets a stop flag, drains the queue so a blocked ``put`` wakes, joins
    the thread, and the source iterator is closed — no leaked thread
    pinning decoded blocks in a long-lived server process."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=queue_size)
    done = object()
    stop = threading.Event()
    failure = []

    def put(item) -> bool:
        """Bounded put that gives up once the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for b in block_iter:
                if not put(b):
                    return
        except BaseException as e:  # re-raised on the consumer side
            failure.append(e)
        finally:
            close = getattr(block_iter, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
            put(done)

    t = threading.Thread(target=produce, daemon=True,
                         name="pio-block-decode")
    t.start()
    try:
        while True:
            b = q.get()
            if b is done:
                break
            yield b
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=10)
    if failure:
        raise failure[0]


def events_to_columnar(events: Iterable[Event],
                       value_property: Optional[str] = None,
                       default_value: float = 1.0,
                       strict: bool = True) -> ColumnarEvents:
    """Generic Event-objects -> columnar conversion (backend fallback).

    ``value_property`` names the DataMap field to extract as the value
    column (e.g. ``"rating"``); rows without it (or with JSON null) get
    ``default_value`` — the template convention where a ``view`` event
    counts as an implicit 1.0 (``DataSource.scala:44-56``). A present but
    non-numeric value (string, bool, list, ...) raises ``ValueError`` when
    ``strict`` (matching ``DataMap.get(name, float)``'s loud failure);
    ``strict=False`` maps it to ``default_value``.
    """
    ents, tgts, vals, times, names = [], [], [], [], []
    for e in events:
        ents.append(e.entity_id)
        tgts.append(e.target_entity_id)
        times.append(e.event_time.timestamp())
        names.append(e.event)
        v = default_value
        if value_property is not None and value_property in e.properties:
            raw = e.properties[value_property]
            if isinstance(raw, (int, float)) and not isinstance(raw, bool):
                v = float(raw)
            elif raw is not None and strict:
                raise ValueError(
                    f"property {value_property!r} of event "
                    f"{e.event_id or e.event!r} is non-numeric: {raw!r}")
        vals.append(v)
    n = len(ents)
    return ColumnarEvents(
        entity_ids=np.asarray(ents, dtype=object) if n
        else np.empty(0, dtype=object),
        target_ids=np.asarray(tgts, dtype=object) if n
        else np.empty(0, dtype=object),
        values=np.asarray(vals, dtype=np.float32),
        event_times=np.asarray(times, dtype=np.float64),
        events=np.asarray(names, dtype=object) if n
        else np.empty(0, dtype=object),
    )
