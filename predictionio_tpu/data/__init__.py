"""Data layer: event model, property bags, storage backends, stores.

Capability parity with the reference ``data`` module
(``/root/reference/data/src/main/scala/io/prediction/data/``), re-designed
for a Python/JAX host runtime: DAOs are plain classes behind a registry,
parallel reads return numpy column batches (the TPU ingest format) instead
of Spark RDDs.
"""
