"""Third-party event ingestion connectors.

Parity target: ``data/.../webhooks/`` — ``JsonConnector``/``FormConnector``
traits, the segment.io JSON connector and the MailChimp form connector,
and the registry consulted by the event server's ``/webhooks/<name>``
routes (``api/WebhooksConnectors.scala:26-32``).

Connectors emit event JSON (a plain dict), never ``Event`` objects — the
server parses the JSON through the one canonical path so validation is
uniform (``ConnectorUtil.scala:33-45``).
"""

from __future__ import annotations

import abc
from typing import Dict


class ConnectorException(ValueError):
    """Malformed/unsupported third-party payload (ConnectorException.scala)."""


class JsonConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_json(self, data: dict) -> dict: ...


class FormConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_json(self, data: Dict[str, str]) -> dict: ...


from predictionio_tpu.data.webhooks.mailchimp import MailChimpConnector  # noqa: E402
from predictionio_tpu.data.webhooks.segmentio import SegmentIOConnector  # noqa: E402

JSON_CONNECTORS: Dict[str, JsonConnector] = {
    "segmentio": SegmentIOConnector(),
}

FORM_CONNECTORS: Dict[str, FormConnector] = {
    "mailchimp": MailChimpConnector(),
}
