"""segment.io spec-v2 webhook → event JSON.

Parity target: ``data/.../webhooks/segmentio/SegmentIOConnector.scala``:
the six message types (identify/track/alias/page/screen/group) map to an
event named after the type, entityType ``user``, entityId from
``userId``/``anonymousId``, eventTime from ``timestamp``, and
type-specific properties (plus the ``context`` object when present).
"""

from __future__ import annotations

from typing import Optional

from predictionio_tpu.data import webhooks


class SegmentIOConnector(webhooks.JsonConnector):

    def to_event_json(self, data: dict) -> dict:
        if "version" not in data:
            raise webhooks.ConnectorException(
                "Failed to get segment.io API version.")
        typ = data.get("type")
        extractor = {
            "identify": self._identify,
            "track": self._track,
            "alias": self._alias,
            "page": self._page,
            "screen": self._screen,
            "group": self._group,
        }.get(typ or "")
        if extractor is None:
            raise webhooks.ConnectorException(
                f"Cannot convert unknown type {typ} to event JSON.")
        try:
            props = extractor(data)
        except KeyError as e:
            raise webhooks.ConnectorException(
                f"Cannot convert {data} to event JSON. missing field {e}")
        return self._to_json(data, typ, props)

    # -- per-type event properties (SegmentIOConnector.scala:103-146) ------
    def _identify(self, data: dict) -> dict:
        return {"traits": data.get("traits")}

    def _track(self, data: dict) -> dict:
        return {"properties": data.get("properties"),
                "event": data["event"]}

    def _alias(self, data: dict) -> dict:
        return {"previous_id": data["previousId"]
                if "previousId" in data else data["previous_id"]}

    def _page(self, data: dict) -> dict:
        return {"name": data.get("name"),
                "properties": data.get("properties")}

    def _screen(self, data: dict) -> dict:
        return {"name": data.get("name"),
                "properties": data.get("properties")}

    def _group(self, data: dict) -> dict:
        return {"group_id": data.get("groupId", data.get("group_id")),
                "traits": data.get("traits")}

    def _to_json(self, data: dict, typ: str, event_props: dict) -> dict:
        user_id: Optional[str] = (
            data.get("user_id") or data.get("userId")
            or data.get("anonymous_id") or data.get("anonymousId"))
        if user_id is None:
            raise webhooks.ConnectorException(
                "there was no `userId` or `anonymousId` in the common fields.")
        properties = {k: v for k, v in event_props.items() if v is not None}
        context = data.get("context")
        if context is not None:
            properties["context"] = context
        return {
            "event": typ,
            "entityType": "user",
            "entityId": str(user_id),
            "eventTime": data.get("timestamp"),
            "properties": properties,
        }
