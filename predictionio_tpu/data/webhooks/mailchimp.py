"""MailChimp form-encoded webhook → event JSON.

Parity target: ``data/.../webhooks/mailchimp/MailChimpConnector.scala`` —
the six types (subscribe/unsubscribe/profile/upemail/cleaned/campaign)
with the same entity/target mapping and property layout; ``fired_at``
("yyyy-MM-dd HH:mm:ss", UTC) becomes ISO-8601 eventTime.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict

from predictionio_tpu.data import webhooks

UTC = _dt.timezone.utc


def parse_mailchimp_datetime(s: str) -> str:
    try:
        t = _dt.datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=UTC)
    except ValueError as e:
        raise webhooks.ConnectorException(f"invalid fired_at: {s!r} ({e})")
    return t.isoformat()


class MailChimpConnector(webhooks.FormConnector):

    def to_event_json(self, data: Dict[str, str]) -> dict:
        typ = data.get("type")
        handler = {
            "subscribe": self._subscribe,
            "unsubscribe": self._unsubscribe,
            "profile": self._profile,
            "upemail": self._upemail,
            "cleaned": self._cleaned,
            "campaign": self._campaign,
        }.get(typ or "")
        if typ is None:
            raise webhooks.ConnectorException(
                "The field 'type' is required for MailChimp data.")
        if handler is None:
            raise webhooks.ConnectorException(
                f"Cannot convert unknown MailChimp data type {typ} "
                "to event JSON")
        try:
            return handler(data)
        except KeyError as e:
            raise webhooks.ConnectorException(
                f"MailChimp {typ} data is missing field {e}")

    def _merges(self, data: Dict[str, str]) -> dict:
        merges = {
            "EMAIL": data["data[merges][EMAIL]"],
            "FNAME": data["data[merges][FNAME]"],
            "LNAME": data["data[merges][LNAME]"],
        }
        if "data[merges][INTERESTS]" in data:
            merges["INTERESTS"] = data["data[merges][INTERESTS]"]
        return merges

    def _subscribe(self, data: Dict[str, str]) -> dict:
        return {
            "event": "subscribe",
            "entityType": "user",
            "entityId": data["data[id]"],
            "targetEntityType": "list",
            "targetEntityId": data["data[list_id]"],
            "eventTime": parse_mailchimp_datetime(data["fired_at"]),
            "properties": {
                "email": data["data[email]"],
                "email_type": data["data[email_type]"],
                "merges": self._merges(data),
                "ip_opt": data["data[ip_opt]"],
                "ip_signup": data["data[ip_signup]"],
            },
        }

    def _unsubscribe(self, data: Dict[str, str]) -> dict:
        return {
            "event": "unsubscribe",
            "entityType": "user",
            "entityId": data["data[id]"],
            "targetEntityType": "list",
            "targetEntityId": data["data[list_id]"],
            "eventTime": parse_mailchimp_datetime(data["fired_at"]),
            "properties": {
                "action": data["data[action]"],
                "reason": data["data[reason]"],
                "email": data["data[email]"],
                "email_type": data["data[email_type]"],
                "merges": self._merges(data),
                "ip_opt": data["data[ip_opt]"],
                "campaign_id": data["data[campaign_id]"],
            },
        }

    def _profile(self, data: Dict[str, str]) -> dict:
        return {
            "event": "profile",
            "entityType": "user",
            "entityId": data["data[id]"],
            "targetEntityType": "list",
            "targetEntityId": data["data[list_id]"],
            "eventTime": parse_mailchimp_datetime(data["fired_at"]),
            "properties": {
                "email": data["data[email]"],
                "email_type": data["data[email_type]"],
                "merges": self._merges(data),
                "ip_opt": data["data[ip_opt]"],
            },
        }

    def _upemail(self, data: Dict[str, str]) -> dict:
        return {
            "event": "upemail",
            "entityType": "user",
            "entityId": data["data[new_id]"],
            "targetEntityType": "list",
            "targetEntityId": data["data[list_id]"],
            "eventTime": parse_mailchimp_datetime(data["fired_at"]),
            "properties": {
                "new_email": data["data[new_email]"],
                "old_email": data["data[old_email]"],
            },
        }

    def _cleaned(self, data: Dict[str, str]) -> dict:
        return {
            "event": "cleaned",
            "entityType": "list",
            "entityId": data["data[list_id]"],
            "eventTime": parse_mailchimp_datetime(data["fired_at"]),
            "properties": {
                "campaignId": data["data[campaign_id]"],
                "reason": data["data[reason]"],
                "email": data["data[email]"],
            },
        }

    def _campaign(self, data: Dict[str, str]) -> dict:
        return {
            "event": "campaign",
            "entityType": "campaign",
            "entityId": data["data[id]"],
            "targetEntityType": "list",
            "targetEntityId": data["data[list_id]"],
            "eventTime": parse_mailchimp_datetime(data["fired_at"]),
            "properties": {
                "subject": data["data[subject]"],
                "status": data["data[status]"],
                "reason": data["data[reason]"],
            },
        }
