"""Fold ``$set/$unset/$delete`` events into current entity property state.

Parity target: reference ``LEventAggregator.scala:39-132`` /
``PEventAggregator.scala``. Semantics (dataMapAggregator, :91-112):

- ``$set``    : merge event properties over current state (event wins)
- ``$unset``  : remove the event's property keys from current state;
                a ``$unset`` before any ``$set`` leaves state nonexistent
- ``$delete`` : reset state to nonexistent
- other events: ignored entirely (do not touch first/lastUpdated)

Events are folded in ``event_time`` order; first/lastUpdated track the
min/max event time over the special events seen.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from predictionio_tpu.data.datamap import DataMap, PropertyMap
from predictionio_tpu.data.event import Event

AGGREGATOR_EVENT_NAMES = ("$set", "$unset", "$delete")


def _fold(events: Iterable[Event]) -> Optional[PropertyMap]:
    dm: Optional[DataMap] = None
    first = None
    last = None
    for e in sorted(events, key=lambda ev: ev.event_time):
        if e.event == "$set":
            dm = e.properties if dm is None else dm.merged(e.properties)
        elif e.event == "$unset":
            dm = None if dm is None else dm.without(list(e.properties.keySet()))
        elif e.event == "$delete":
            dm = None
        else:
            continue  # non-special events do not affect aggregation
        t = e.event_time
        first = t if first is None or t < first else first
        last = t if last is None or t > last else last
    if dm is None:
        return None
    return PropertyMap(dm.fields, first_updated=first, last_updated=last)


def aggregate_properties_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Aggregate one entity's events (LEventAggregator.scala:69-87)."""
    return _fold(events)


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Group by entityId then fold; entities whose state resolved to

    nonexistent (deleted / never set) are dropped (LEventAggregator.scala:39-57).
    """
    by_entity: Dict[str, list] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: Dict[str, PropertyMap] = {}
    for eid, evs in by_entity.items():
        pm = _fold(evs)
        if pm is not None:
            out[eid] = pm
    return out
