"""Fold ``$set/$unset/$delete`` events into current entity property state.

Parity target: reference ``LEventAggregator.scala:39-132`` /
``PEventAggregator.scala``. Semantics (dataMapAggregator, :91-112):

- ``$set``    : merge event properties over current state (event wins)
- ``$unset``  : remove the event's property keys from current state;
                a ``$unset`` before any ``$set`` leaves state nonexistent
- ``$delete`` : reset state to nonexistent
- other events: ignored entirely (do not touch first/lastUpdated)

Events are folded in ``event_time`` order; first/lastUpdated track the
min/max event time over the special events seen.

The fold is exposed at three grains so storage backends can keep the
aggregate MATERIALIZED instead of replaying full histories:

- :func:`fold_event` — the single-event step ``(state, event) -> state``
  used by write-through backends (fold at insert time);
- :func:`aggregate_properties_single` / :func:`aggregate_properties` —
  the replay fold over a (sorted) event stream, unchanged semantics;
- :class:`EntityState` — the per-entity accumulator, JSON-serializable
  (``to_record``/``from_record``) for snapshot/table persistence.

Incremental correctness contract: folding an event whose
``event_time >= state.last_updated`` is exactly equivalent to inserting
it into the replay (stable sort puts later arrivals after earlier ones
on ties). An event OLDER than ``state.last_updated`` is out-of-order —
the caller must re-fold that entity's history instead.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Dict, Iterable, Optional

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event

AGGREGATOR_EVENT_NAMES = ("$set", "$unset", "$delete")


@dataclasses.dataclass(frozen=True)
class EntityState:
    """Accumulated property state of ONE entity after folding its
    special events in time order.

    ``fields is None`` is a TOMBSTONE: the entity's state is currently
    nonexistent (``$delete``d, or only ``$unset`` seen) but its
    first/last updated times keep tracking every special event — a later
    ``$set`` must resurrect the entity with the original
    ``first_updated`` (LEventAggregatorSpec: set-after-delete).
    """

    fields: Optional[Dict] = None
    first_updated: Optional[_dt.datetime] = None
    last_updated: Optional[_dt.datetime] = None

    @property
    def exists(self) -> bool:
        return self.fields is not None

    def to_property_map(self) -> Optional[PropertyMap]:
        if self.fields is None:
            return None
        return PropertyMap(self.fields, first_updated=self.first_updated,
                           last_updated=self.last_updated)

    # -- persistence (sqlite entity_props table / jsonlfs snapshot) -------
    def to_record(self) -> list:
        """JSON-friendly ``[fields_or_null, first_epoch, last_epoch]``."""
        return [self.fields,
                None if self.first_updated is None
                else self.first_updated.timestamp(),
                None if self.last_updated is None
                else self.last_updated.timestamp()]

    @classmethod
    def from_record(cls, rec) -> "EntityState":
        def ts(x):
            return None if x is None else _dt.datetime.fromtimestamp(
                x, tz=_dt.timezone.utc)

        return cls(fields=rec[0] if rec[0] is None else dict(rec[0]),
                   first_updated=ts(rec[1]), last_updated=ts(rec[2]))


def fold_event(state: Optional[EntityState],
               event: Event) -> Optional[EntityState]:
    """One fold step: apply ``event`` to ``state`` and return the new
    state (the input is never mutated). Non-special events return the
    state unchanged. Callers must apply events in event-time order with
    ties in arrival order — see the module docstring's incremental
    contract for what that buys write-through backends."""
    name = event.event
    if name not in AGGREGATOR_EVENT_NAMES:
        return state
    fields = None if state is None else state.fields
    if name == "$set":
        merged = dict(fields) if fields else {}
        merged.update(event.properties.fields)
        fields = merged
    elif name == "$unset":
        if fields is not None:
            drop = event.properties.keySet()
            fields = {k: v for k, v in fields.items() if k not in drop}
    else:  # $delete
        fields = None
    t = event.event_time
    first = t if state is None or state.first_updated is None \
        or t < state.first_updated else state.first_updated
    last = t if state is None or state.last_updated is None \
        or t > state.last_updated else state.last_updated
    return EntityState(fields=fields, first_updated=first, last_updated=last)


def fold_events(events: Iterable[Event],
                state: Optional[EntityState] = None) -> Optional[EntityState]:
    """Fold one entity's events (sorted by event_time, stable over input
    order) into ``state``. The replay building block: with ``state=None``
    this IS the reference fold; with a snapshot state it folds a delta."""
    for e in sorted(events, key=lambda ev: ev.event_time):
        state = fold_event(state, e)
    return state


def _fold(events: Iterable[Event]) -> Optional[PropertyMap]:
    state = fold_events(events)
    return None if state is None else state.to_property_map()


def aggregate_properties_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Aggregate one entity's events (LEventAggregator.scala:69-87)."""
    return _fold(events)


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Group by entityId then fold; entities whose state resolved to

    nonexistent (deleted / never set) are dropped (LEventAggregator.scala:39-57).
    """
    by_entity: Dict[str, list] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: Dict[str, PropertyMap] = {}
    for eid, evs in by_entity.items():
        pm = _fold(evs)
        if pm is not None:
            out[eid] = pm
    return out


def aggregate_states(events: Iterable[Event]) -> Dict[str, EntityState]:
    """Like :func:`aggregate_properties` but KEEPS tombstones — the shape
    materialized state tables persist (a tombstone must survive so a
    re-``$set`` after ``$delete`` retains ``first_updated``)."""
    by_entity: Dict[str, list] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: Dict[str, EntityState] = {}
    for eid, evs in by_entity.items():
        st = fold_events(evs)
        if st is not None:
            out[eid] = st
    return out


def states_to_property_maps(
        states: Dict[str, EntityState]) -> Dict[str, PropertyMap]:
    """Materialized states -> the aggregate_properties result shape
    (tombstones dropped)."""
    out: Dict[str, PropertyMap] = {}
    for eid, st in states.items():
        pm = st.to_property_map()
        if pm is not None:
            out[eid] = pm
    return out
