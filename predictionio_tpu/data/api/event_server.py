"""Event-collection REST server (:7070).

Parity target: ``data/.../api/EventServer.scala:90-632`` — same routes,
same status codes, same JSON shapes:

- ``GET /``                        → ``{"status": "alive"}``
- ``POST /events.json``            → 201 ``{"eventId": ...}``
- ``GET /events.json``             → filtered query, default limit 20
- ``GET|DELETE /events/<id>.json`` → single-event fetch/delete
- ``POST /batch/events.json``      → ≤50 events, per-item statuses
- ``GET /stats.json``              → counters (only with ``stats=True``)
- ``GET /plugins.json`` + ``GET /plugins/<type>/<name>/...``
- ``POST|GET /webhooks/<name>.json|.form``

Auth: ``accessKey`` query param or Basic ``Authorization`` header
(EventServer.scala:90-128); optional ``channel`` query param resolves a
channel name to its ID. The spray/akka stack is replaced by a
thread-per-request stdlib HTTP server: the storage DAOs are blocking and
thread-safe, so threads are the idiomatic host-side concurrency here
(the TPU is never on this path).
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import hashlib
import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from predictionio_tpu.utils.http_instrumentation import (
    SeveringThreadingHTTPServer,
)
from typing import Any, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.data import storage
from predictionio_tpu.data.api.plugins import EventInfo, EventServerPluginContext
from predictionio_tpu.data.api.stats import StatsKeeper
from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    validate_event,
)
from predictionio_tpu.data.storage.base import UNSET
from predictionio_tpu.utils import metrics
from predictionio_tpu.utils.http_instrumentation import (
    InstrumentedHandlerMixin,
)

logger = logging.getLogger("pio.eventserver")

MAX_EVENTS_PER_BATCH = 50  # EventServer.scala:68
DEFAULT_QUERY_LIMIT = 20   # EventServer.scala:352


@dataclasses.dataclass
class EventServerConfig:
    """EventServerConfig (EventServer.scala:572-576).

    ``service_key`` additionally enables the ``/storage/*`` wire: the
    remote-DAO lane the ``resthttp`` storage backend speaks, so training
    on one machine can read events served from another — the
    architecture ``Storage.scala:360-391`` gets from remote HBase/JDBC
    services. It is a storage credential (the analog of the DB password
    in the reference's storage config), distinct from per-app access
    keys; unset = the wire is disabled.

    ``server_config_path`` names a server.json whose ``ssl`` section
    (certfile/keyfile) serves the whole API over TLS — net-new vs the
    reference's plain-HTTP event server, and what keeps access keys and
    the service key off the wire in cleartext."""
    ip: str = "0.0.0.0"
    port: int = 7070
    stats: bool = False
    service_key: Optional[str] = None
    server_config_path: Optional[str] = None


@dataclasses.dataclass
class AuthData:
    """Resolved access-key auth (EventServer.scala:87)."""
    app_id: int
    channel_id: Optional[int]
    events: Sequence[str]


class _HttpError(Exception):
    def __init__(self, status: int, payload: Dict[str, Any]):
        super().__init__(payload.get("message", ""))
        self.status = status
        self.payload = payload


class EventServer:
    """The daemon. ``start()`` binds and serves on a background thread."""

    def __init__(self, config: Optional[EventServerConfig] = None,
                 plugin_context: Optional[EventServerPluginContext] = None,
                 reg: Optional[storage.StorageRegistry] = None):
        self.config = config or EventServerConfig()
        self.registry = reg or storage.registry()
        self.event_client = self.registry.get_levents()
        self.access_keys_client = self.registry.get_metadata_access_keys()
        self.channels_client = self.registry.get_metadata_channels()
        self.stats_keeper = StatsKeeper() if config.stats else None
        # client-chosen event names are a label value: cap the distinct
        # series one SERVER will ever mint (registry series never evict);
        # per-instance so one exhausted server cannot poison another
        self._event_label = metrics.BoundedLabel(cap=100)
        self.plugin_context = plugin_context or EventServerPluginContext()
        # (app, channel, body-digest) -> acked count of recently
        # fully-committed /storage appends. The wire retries a
        # byte-identical body, so a retried POST that hits here is a
        # pure replay of a committed append — answered in O(hash),
        # never rescanning the store. A miss (server restart, partial
        # commit) falls back to the exact existence scan.
        self._append_seen: "collections.OrderedDict[tuple, int]" = \
            collections.OrderedDict()
        self._append_seen_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "EventServer":
        from predictionio_tpu.common import SSLConfiguration
        from predictionio_tpu.common.auth import (
            ServerConfig as AuthServerConfig,
        )

        server = self

        class Handler(_EventHandler):
            event_server = server

        # TLS only when a server.json is NAMED: the cwd/server.json
        # fallback ServerConfig.load applies elsewhere must not flip a
        # plain `pio eventserver` to HTTPS because a deploy config
        # happens to sit in the working directory
        if self.config.server_config_path:
            sslc = SSLConfiguration(
                AuthServerConfig.load(self.config.server_config_path))
        else:
            sslc = SSLConfiguration(AuthServerConfig())
        self.scheme = "https" if sslc.enabled else "http"
        self._httpd = SeveringThreadingHTTPServer(
            (self.config.ip, self.config.port), Handler)
        if sslc.enabled:
            sslc.wrap_server(self._httpd)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pio-eventserver",
            daemon=True)
        self._thread.start()
        logger.info("Event server started on %s://%s:%d", self.scheme,
                    *self.address)
        return self

    @property
    def address(self) -> Tuple[str, int]:
        assert self._httpd is not None, "server not started"
        host, port = self._httpd.server_address[:2]
        return str(host), int(port)

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def serve_forever(self) -> None:
        if self._httpd is None:
            self.start()
        assert self._thread is not None
        self._thread.join()

    # -- auth (EventServer.scala:90-128) -----------------------------------
    def authenticate(self, query: Dict[str, List[str]],
                     headers) -> AuthData:
        key_param = _first(query, "accessKey")
        channel_param = _first(query, "channel")
        if key_param is not None:
            k = self.access_keys_client.get(key_param)
            if k is None:
                raise _HttpError(401, {"message": "Invalid accessKey."})
            if channel_param is not None:
                channel_map = {
                    c.name: c.id
                    for c in self.channels_client.get_by_appid(k.appid)
                }
                if channel_param not in channel_map:
                    raise _HttpError(
                        401, {"message": f"Invalid channel '{channel_param}'."})
                return AuthData(k.appid, channel_map[channel_param], k.events)
            return AuthData(k.appid, None, k.events)
        auth_header = headers.get("Authorization")
        if auth_header and auth_header.startswith("Basic "):
            try:
                decoded = base64.b64decode(
                    auth_header[len("Basic "):]).decode("utf-8")
            except Exception:
                raise _HttpError(401, {"message": "Invalid accessKey."})
            app_access_key = decoded.strip().split(":")[0]
            k = self.access_keys_client.get(app_access_key)
            if k is None:
                raise _HttpError(401, {"message": "Invalid accessKey."})
            return AuthData(k.appid, None, k.events)
        raise _HttpError(401, {"message": "Missing accessKey."})

    # -- route logic -------------------------------------------------------
    def _bookkeep(self, app_id: int, status: int, event: Event) -> None:
        # per-event-type ingest counters are always on (registry-gated),
        # unlike the reference's opt-in --stats windows
        metrics.INGEST_EVENTS.inc(app_id=str(app_id),
                                  event=self._event_label(event.event),
                                  status=str(status))
        if self.stats_keeper is not None:
            self.stats_keeper.bookkeeping(app_id, status, event)

    def _insert_one(self, event: Event, auth: AuthData) -> Tuple[int, Dict]:
        """Single-event insert path (EventServer.scala:259-299)."""
        if auth.events and event.event not in auth.events:
            self._bookkeep(auth.app_id, 403, event)
            return 403, {"message": f"{event.event} events are not allowed"}
        info = EventInfo(auth.app_id, auth.channel_id, event)
        for blocker in self.plugin_context.input_blockers.values():
            try:
                blocker.process(info, self.plugin_context)
            except ValueError as e:
                self._bookkeep(auth.app_id, 403, event)
                return 403, {"message": str(e)}
        event_id = self.event_client.insert(event, auth.app_id,
                                            auth.channel_id)
        for sniffer in self.plugin_context.input_sniffers.values():
            try:
                sniffer.process(info, self.plugin_context)
            except Exception:
                logger.exception("input sniffer failed")
        self._bookkeep(auth.app_id, 201, event)
        return 201, {"eventId": str(event_id)}

    def post_events(self, auth: AuthData, body: bytes) -> Tuple[int, Any]:
        event = _parse_event(body)
        return self._insert_one(event, auth)

    def post_batch(self, auth: AuthData, body: bytes) -> Tuple[int, Any]:
        """Batch insert, per-item status (EventServer.scala:374-440)."""
        try:
            items = json.loads(body.decode("utf-8"))
            if not isinstance(items, list):
                raise ValueError("batch body must be a JSON array")
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
            return 400, {"message": f"{e}"}
        if len(items) > MAX_EVENTS_PER_BATCH:
            return 400, {"message":
                         "Batch request must have less than or equal to "
                         f"{MAX_EVENTS_PER_BATCH} events"}
        results = []
        for item in items:
            try:
                event = _parse_event_dict(item)
            except EventValidationError as e:
                results.append({"status": 400, "message": str(e)})
                continue
            try:
                status, payload = self._insert_one(event, auth)
            except Exception as e:  # per-item isolation (scala :404-408)
                results.append({"status": 500, "message": str(e)})
                continue
            entry: Dict[str, Any] = {"status": status}
            entry.update(payload)
            results.append(entry)
        return 200, results

    def get_events(self, auth: AuthData,
                   query: Dict[str, List[str]]) -> Tuple[int, Any]:
        """Filtered query (EventServer.scala:300-372)."""
        reversed_ = _first(query, "reversed") in ("true", "True", "1")
        entity_type = _first(query, "entityType")
        entity_id = _first(query, "entityId")
        if reversed_ and (entity_type is None or entity_id is None):
            return 400, {"message":
                         "the parameter reversed can only be used with both "
                         "entityType and entityId specified."}
        try:
            from predictionio_tpu.data.event import _parse_time
            start_time = _parse_time(_first(query, "startTime"))
            until_time = _parse_time(_first(query, "untilTime"))
            limit_s = _first(query, "limit")
            limit = int(limit_s) if limit_s is not None else DEFAULT_QUERY_LIMIT
        except (EventValidationError, ValueError) as e:
            return 400, {"message": f"{e}"}
        event_name = _first(query, "event")
        tet = _first(query, "targetEntityType")
        tei = _first(query, "targetEntityId")
        events = list(self.event_client.find(
            app_id=auth.app_id,
            channel_id=auth.channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=[event_name] if event_name else None,
            target_entity_type=tet if tet is not None else UNSET,
            target_entity_id=tei if tei is not None else UNSET,
            limit=limit,
            reversed=reversed_,
        ))
        if not events:
            return 404, {"message": "Not Found"}
        return 200, [e.to_dict() for e in events]

    def get_event(self, auth: AuthData, event_id: str) -> Tuple[int, Any]:
        event = self.event_client.get(event_id, auth.app_id, auth.channel_id)
        if event is None:
            return 404, {"message": "Not Found"}
        return 200, event.to_dict()

    def delete_event(self, auth: AuthData, event_id: str) -> Tuple[int, Any]:
        found = self.event_client.delete(event_id, auth.app_id,
                                         auth.channel_id)
        if found:
            return 200, {"message": "Found"}
        return 404, {"message": "Not Found"}

    def get_stats(self, auth: AuthData) -> Tuple[int, Any]:
        if self.stats_keeper is None:
            return 404, {"message": "To see stats, launch Event Server with "
                                    "--stats argument."}
        payload = self.stats_keeper.get(auth.app_id)
        # per-(app, channel) stream-end watermark (last appended event id
        # + time + the tail cursor): the observability hook the online
        # fold-in freshness story reads — "how far does the stream go"
        # next to the query server's "how far have I folded"
        try:
            payload["tailWatermark"] = self.event_client.tail_watermark(
                auth.app_id, auth.channel_id)
        except Exception:
            payload["tailWatermark"] = None  # backend keeps no cheap tail
        # richer than the reference shape: the process-wide registry
        # snapshot rides along. The caller authed for ONE app, so
        # app-labeled series are filtered to it — the reference's
        # /stats.json was app-scoped and this view must not widen it
        snap = metrics.registry().snapshot()
        for fam in snap.values():
            fam["series"] = [
                s for s in fam["series"]
                if s["labels"].get("app_id") in (None, str(auth.app_id))]
        payload["metrics"] = {k: v for k, v in snap.items() if v["series"]}
        return 200, payload

    def post_webhooks(self, auth: AuthData, name: str, form: bool,
                      body: bytes,
                      content_type: str) -> Tuple[int, Any]:
        """Webhook ingestion (api/Webhooks.scala:44-151)."""
        from predictionio_tpu.data import webhooks

        if form:
            connector = webhooks.FORM_CONNECTORS.get(name)
        else:
            connector = webhooks.JSON_CONNECTORS.get(name)
        if connector is None:
            return 404, {"message":
                         f"webhooks connection for {name} is not supported."}
        try:
            if form:
                fields = dict(urllib.parse.parse_qsl(body.decode("utf-8")))
                event_json = connector.to_event_json(fields)
            else:
                data = json.loads(body.decode("utf-8"))
                if not isinstance(data, dict):
                    raise webhooks.ConnectorException(
                        "webhook body must be a JSON object")
                event_json = connector.to_event_json(data)
            event = _parse_event_dict(event_json)
        except (webhooks.ConnectorException, EventValidationError,
                json.JSONDecodeError, UnicodeDecodeError) as e:
            return 400, {"message": f"{e}"}
        event_id = self.event_client.insert(event, auth.app_id,
                                            auth.channel_id)
        self._bookkeep(auth.app_id, 201, event)
        return 201, {"eventId": str(event_id)}

    def get_webhooks(self, auth: AuthData, name: str,
                     form: bool) -> Tuple[int, Any]:
        from predictionio_tpu.data import webhooks

        reg = webhooks.FORM_CONNECTORS if form else webhooks.JSON_CONNECTORS
        if name in reg:
            return 200, {"message": "Ok"}
        return 404, {"message":
                     f"webhooks connection for {name} is not supported."}

    # -- storage wire (/storage/*, service-key authed) ---------------------
    # The remote-DAO lane: the `resthttp` backend's LEvents/PEvents client
    # speaks these routes, so engines train against THIS server's event
    # store from another machine/process (Storage.scala:360-391 remote-DAO
    # architecture; bulk reads are the HBPEvents.scala:83-89 analog —
    # partition bytes shipped raw, decoded client-side by the native
    # codec). The service key is a storage credential like the
    # reference's DB password: callers are trusted peers, and the append
    # lane takes pre-validated JSONL (the client DAO validates before
    # serializing, as the jsonlfs fast lane does).

    def storage_auth(self, query: Dict[str, List[str]]) -> None:
        import hmac

        sk = self.config.service_key
        if not sk:
            raise _HttpError(403, {
                "message": "storage wire disabled — start the event "
                           "server with a service key"})
        given = _first(query, "serviceKey") or ""
        if not hmac.compare_digest(given, sk):
            raise _HttpError(401, {"message": "Invalid serviceKey."})

    @staticmethod
    def _storage_scope(query) -> Tuple[int, Optional[int]]:
        app_id = _first(query, "appId")
        if app_id is None:
            raise _HttpError(400, {"message": "appId is required"})
        ch = _first(query, "channelId")
        # malformed numbers are client errors, not 500s
        return (_int_param(app_id, "appId"),
                _int_param(ch, "channelId") if ch is not None else None)

    def storage_init(self, query) -> Tuple[int, Any]:
        app_id, ch = self._storage_scope(query)
        return 200, {"ok": bool(self.event_client.init(app_id, ch))}

    def storage_remove(self, query) -> Tuple[int, Any]:
        app_id, ch = self._storage_scope(query)
        return 200, {"ok": bool(self.event_client.remove(app_id, ch))}

    _APPEND_SEEN_CAP = 512

    def storage_append(self, query, body: bytes,
                       retried: bool = False) -> Tuple[int, Any]:
        app_id, ch = self._storage_scope(query)
        digest = (app_id, ch, hashlib.sha256(body).digest())
        if retried:
            acked = self._recent_append_count(digest)
            if acked is not None:
                logger.info("storage append retry: byte-identical replay"
                            " of a committed append; skipped")
                return 200, {"count": acked}
        lines = [ln for ln in body.decode("utf-8").split("\n")
                 if ln.strip()]
        # the ack (and the replay-cache entry) count the LOGICAL lines
        # of this request: after the dedup scan drops already-committed
        # lines, the whole body is durable — acking the post-dedup
        # remainder would make the same retried request answer 10 on a
        # cache hit but 0 after a server restart
        n_acked = len(lines)
        le = self.event_client
        if retried and lines:
            lines = self._dedup_retried_lines(lines, app_id, ch)
        if hasattr(le, "append_raw_lines"):
            le.append_raw_lines(lines, app_id, ch)
        else:
            le.insert_batch([Event.from_json(ln) for ln in lines],
                            app_id, ch)
        self._remember_append(digest, n_acked)
        return 200, {"count": n_acked}

    def _recent_append_count(self, digest: tuple) -> Optional[int]:
        with self._append_seen_lock:
            acked = self._append_seen.get(digest)
            if acked is not None:
                self._append_seen.move_to_end(digest)
            return acked

    def _remember_append(self, digest: tuple, count: int) -> None:
        with self._append_seen_lock:
            self._append_seen[digest] = count
            self._append_seen.move_to_end(digest)
            while len(self._append_seen) > self._APPEND_SEEN_CAP:
                self._append_seen.popitem(last=False)

    def _dedup_retried_lines(self, lines, app_id: int,
                             ch: Optional[int]):
        """Exactly-once for RETRIED appends (``X-Idempotency-Retry``):
        the client's first attempt may have committed before its
        response was lost — a blind re-append would duplicate every
        acknowledged-but-unacked event. Backends whose insert is an
        id-keyed upsert (sqlite, memory) dedup natively; append-only
        backends (jsonlfs) get one existence scan here. The scan runs
        ONLY on retried requests that missed the byte-identical replay
        cache (server restarted, or the first attempt only partially
        committed), so the bulk-ingest hot path pays nothing and the
        common retry pays a hash, not a store scan."""
        from predictionio_tpu.data.storage.observed import unwrap

        le = self.event_client
        if getattr(unwrap(le), "idempotent_event_writes", False):
            return lines
        existing = {e.event_id
                    for e in le.find(app_id=app_id, channel_id=ch)}
        kept = []
        for ln in lines:
            try:
                eid = json.loads(ln).get("eventId")
            except (json.JSONDecodeError, AttributeError):
                eid = None
            if eid and eid in existing:
                continue
            kept.append(ln)
        if len(kept) != len(lines):
            logger.info("storage append retry: deduplicated %d of %d "
                        "already-committed events",
                        len(lines) - len(kept), len(lines))
        return kept

    def health_checks(self) -> Dict[str, bool]:
        """Readiness checks for ``GET /healthz``: the event store's
        circuit breaker must not be refusing calls (liveness is the
        response itself)."""
        from predictionio_tpu.utils import resilience

        return {"storage": resilience.storage_ready(self.event_client)}

    def storage_get_event(self, query, event_id: str) -> Tuple[int, Any]:
        app_id, ch = self._storage_scope(query)
        e = self.event_client.get(event_id, app_id, ch)
        if e is None:
            return 404, {"message": "Not Found"}
        return 200, e.to_dict()

    def storage_delete_event(self, query, event_id: str) -> Tuple[int, Any]:
        app_id, ch = self._storage_scope(query)
        return 200, {"found": bool(
            self.event_client.delete(event_id, app_id, ch))}

    def storage_delete_until(self, query) -> Tuple[int, Any]:
        app_id, ch = self._storage_scope(query)
        until = _time_param(query, "untilTime")
        if until is None:
            return 400, {"message": "untilTime is required"}
        return 200, {"removed":
                     self.event_client.delete_until(app_id, until, ch)}

    def storage_tail(self, query,
                     body: Optional[bytes] = None) -> Tuple[int, Any]:
        """Tail-read wire (``GET``/``POST /storage/tail.json``): the
        remote-DAO lane for ``find_since`` / ``tail_cursor`` /
        ``tail_watermark`` — what a deployed query server's online
        fold-in consumer polls when its event store lives in this
        process. The cursor is the backend's opaque JSON, passed
        through verbatim both ways; POST carries it in the request body
        (a jsonlfs watermark grows one entry per partition, and a large
        store's cursor would overflow the request-line cap as a query
        parameter)."""
        app_id, ch = self._storage_scope(query)
        le = self.event_client
        if _first(query, "watermark") == "true":
            return 200, {"watermark": le.tail_watermark(app_id, ch)}
        if _first(query, "position") == "end":
            return 200, {"cursor": le.tail_cursor(app_id, ch)}
        cursor = None
        limit = None
        if body:
            try:
                parsed = json.loads(body.decode("utf-8"))
                if not isinstance(parsed, dict):
                    raise ValueError("body must be a JSON object")
            except (json.JSONDecodeError, UnicodeDecodeError,
                    ValueError) as e:
                raise _HttpError(400, {"message": f"invalid body: {e}"})
            cursor = parsed.get("cursor")
            if cursor is not None and not isinstance(cursor, dict):
                raise _HttpError(
                    400, {"message": "invalid cursor: must be a JSON "
                                     "object"})
            if parsed.get("limit") is not None:
                limit = _int_param(str(parsed["limit"]), "limit")
        raw = _first(query, "cursor")
        if cursor is None and raw:
            try:
                cursor = json.loads(raw)
                if not isinstance(cursor, dict):
                    raise ValueError("cursor must be a JSON object")
            except (json.JSONDecodeError, ValueError) as e:
                raise _HttpError(400, {"message": f"invalid cursor: {e}"})
        limit_s = _first(query, "limit")
        if limit is None and limit_s is not None:
            limit = _int_param(limit_s, "limit")
        if limit is None:
            # server-side cap: a limit-less tail read would materialize
            # the ENTIRE store as one list + one unchunked response (the
            # bulk-read lane is the streaming /storage/events.jsonl);
            # callers page through the returned cursor
            limit = 10_000
        events, cur = le.find_since(app_id, ch, cursor=cursor, limit=limit)
        return 200, {"events": [e.to_dict() for e in events],
                     "cursor": cur}

    def storage_aggregate(self, query) -> Tuple[int, Any]:
        """Server-side ``aggregate_properties`` for the remote-DAO lane:
        unbounded calls answer from the backend's MATERIALIZED state, so
        a remote training host downloads current entities, not event
        history (the hot `PEventStore.aggregate_properties` shape)."""
        app_id, ch = self._storage_scope(query)
        entity_type = _first(query, "entityType")
        if not entity_type:
            return 400, {"message": "entityType is required"}
        props = self.event_client.aggregate_properties(
            app_id, entity_type, channel_id=ch,
            start_time=_time_param(query, "startTime"),
            until_time=_time_param(query, "untilTime"))
        out = {}
        for eid, pm in props.items():
            rec: Dict[str, Any] = {"properties": pm.fields}
            if pm.first_updated is not None:
                rec["firstUpdatedT"] = pm.first_updated.isoformat()
            if pm.last_updated is not None:
                rec["lastUpdatedT"] = pm.last_updated.isoformat()
            out[eid] = rec
        return 200, out

    _STORAGE_FILTER_KEYS = ("startTime", "untilTime", "entityType",
                            "entityId", "event", "targetEntityType",
                            "targetEntityTypeNull", "targetEntityId",
                            "targetEntityIdNull", "limit", "reversed")

    def storage_stream(self, query):
        """Yield event-JSONL byte chunks for a bulk read.

        Fast lane: when the underlying store is jsonlfs and no content
        filter is requested, the partition files ARE the wire format —
        raw bytes go out with zero parsing. Otherwise events stream
        through the underlying ``find``."""
        app_id, ch = self._storage_scope(query)
        unfiltered = not any(k in query for k in self._STORAGE_FILTER_KEYS)
        le = self.event_client
        from predictionio_tpu.data.storage.jsonlfs import JsonlFsLEvents
        from predictionio_tpu.data.storage.observed import unwrap

        # the fast lane needs the concrete backend behind the metrics
        # wrapper (partition files ARE the wire format)
        raw = unwrap(le)
        if unfiltered and isinstance(raw, JsonlFsLEvents):
            d = raw._dir(app_id, ch)
            def raw_parts():
                for part in raw._parts(d):
                    with open(part, "rb") as f:
                        while True:
                            chunk = f.read(1 << 22)
                            if not chunk:
                                break
                            yield chunk
            return raw_parts()

        tet = _first(query, "targetEntityType")
        if _first(query, "targetEntityTypeNull") == "true":
            tet = None
        elif tet is None:
            tet = UNSET
        tei = _first(query, "targetEntityId")
        if _first(query, "targetEntityIdNull") == "true":
            tei = None
        elif tei is None:
            tei = UNSET
        limit_s = _first(query, "limit")
        events = le.find(
            app_id=app_id, channel_id=ch,
            start_time=_time_param(query, "startTime"),
            until_time=_time_param(query, "untilTime"),
            entity_type=_first(query, "entityType"),
            entity_id=_first(query, "entityId"),
            event_names=query.get("event") or None,
            target_entity_type=tet, target_entity_id=tei,
            limit=_int_param(limit_s, "limit") if limit_s is not None
            else None,
            reversed=_first(query, "reversed") == "true",
        )

        def serialized():
            buf: List[str] = []
            for e in events:
                buf.append(e.to_json())
                if len(buf) >= 2000:
                    yield ("\n".join(buf) + "\n").encode("utf-8")
                    buf.clear()
            if buf:
                yield ("\n".join(buf) + "\n").encode("utf-8")
        return serialized()


def _first(query: Dict[str, List[str]], key: str) -> Optional[str]:
    vals = query.get(key)
    return vals[0] if vals else None


def _int_param(raw: str, name: str) -> int:
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise _HttpError(400, {"message": f"invalid {name}: {raw!r}"})


def _time_param(query: Dict[str, List[str]], name: str):
    from predictionio_tpu.data.event import EventValidationError, _parse_time

    raw = _first(query, name)
    try:
        return _parse_time(raw)
    except (EventValidationError, ValueError):
        raise _HttpError(400, {"message": f"invalid {name}: {raw!r}"})


def _parse_event_dict(d: Any) -> Event:
    if not isinstance(d, dict):
        raise EventValidationError("event JSON must be an object")
    try:
        event = Event.from_dict(d)
    except EventValidationError:
        raise
    except (TypeError, ValueError, AttributeError) as e:
        # malformed field types (tags: 5, properties: "x", ...) are client
        # errors, same contract as validation failures
        raise EventValidationError(str(e)) from e
    validate_event(event)
    return event


def _parse_event(body: bytes) -> Event:
    try:
        d = json.loads(body.decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise _HttpError(400, {"message": f"invalid JSON: {e}"})
    try:
        return _parse_event_dict(d)
    except EventValidationError as e:
        raise _HttpError(400, {"message": str(e)})


class _EventHandler(InstrumentedHandlerMixin, BaseHTTPRequestHandler):
    """Request → route dispatch. One instance per request (threaded)."""

    event_server: EventServer  # injected by EventServer.start
    protocol_version = "HTTP/1.1"
    metrics_server_label = "event"

    # -- plumbing ----------------------------------------------------------
    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("%s - %s", self.address_string(), fmt % args)

    def _body(self) -> bytes:
        return self._request_body

    def _respond_chunked(self, status: int, chunks) -> None:
        """Stream an unbounded byte-chunk iterator (Transfer-Encoding:
        chunked). A failure after the headers go out aborts the
        connection (``_stream_started`` tells ``_dispatch`` a second
        response is impossible) — the client sees a truncated chunked
        stream and raises, never silently-short data."""
        self._status_sent = status
        self.send_response(status)
        self.send_header("Content-Type", "application/x-jsonlines")
        self.send_header("Transfer-Encoding", "chunked")
        rid = getattr(self, "_request_id", None)
        if rid:
            self.send_header("X-Request-ID", rid)
        tp = getattr(self, "_traceparent", None)
        if tp:
            self.send_header("traceparent", tp)
        self.end_headers()
        self._stream_started = True
        for c in chunks:
            if not c:
                continue
            self.wfile.write(f"{len(c):x}\r\n".encode("ascii"))
            self.wfile.write(c)
            self.wfile.write(b"\r\n")
        self.wfile.write(b"0\r\n\r\n")

    # route patterns for metric labels: bounded cardinality, never raw
    # paths (an id or webhook name must not mint a new series)
    def _route_label(self, path: str) -> str:
        if path in ("/", "/healthz", "/metrics", "/stats.json",
                    "/events.json",
                    "/batch/events.json", "/plugins.json", "/traces.json",
                    "/storage/events.jsonl", "/storage/init.json",
                    "/storage/remove.json", "/storage/delete_until.json",
                    "/storage/aggregate.json", "/storage/tail.json"):
            return path
        if path.startswith("/traces/"):
            return "/traces/<id>"
        if path.startswith("/storage/events/"):
            return "/storage/events/<id>.json"
        if path.startswith("/events/"):
            return "/events/<id>.json"
        if path.startswith("/webhooks/"):
            return "/webhooks/<name>"
        if path.startswith("/plugins/"):
            return "/plugins/<type>/<name>"
        return "<other>"

    def _dispatch(self, method: str) -> None:
        parsed = urllib.parse.urlsplit(self.path)
        path = parsed.path.rstrip("/") or "/"
        self._dispatch_instrumented(
            method, path, lambda: self._handle(method, path, parsed))

    def _handle(self, method: str, path: str, parsed) -> None:
        srv = self.event_server
        query = urllib.parse.parse_qs(parsed.query)
        # Drain the request body up-front: every exit path (401, 404, ...)
        # must leave rfile at a message boundary or HTTP/1.1 keep-alive
        # clients would read garbage on the next pipelined request.
        length = int(self.headers.get("Content-Length") or 0)
        self._request_body = self.rfile.read(length) if length else b""
        # per-REQUEST flag on a per-CONNECTION handler instance: a prior
        # successful stream on this keep-alive connection must not make
        # later errors close the socket instead of responding
        self._stream_started = False
        try:
            if path == "/" and method == "GET":
                self._respond(200, {"status": "alive"})
                return
            if path == "/healthz" and method == "GET":
                # liveness + readiness probe: unauthenticated like
                # GET / (a load balancer has no access key)
                self._respond_healthz(srv.health_checks())
                return
            if path == "/metrics" and method == "GET":
                # Prometheus scrape endpoint: unauthenticated like GET /.
                # It is an OPERATOR surface — it carries cross-app
                # operational counters (event-type names, volumes), so
                # bind it to scrape-network interfaces, not the public
                # internet (README "Observability")
                self._respond_prometheus()
                return
            if path == "/traces.json" and method == "GET":
                # trace index/detail are operator surfaces like /metrics
                # (unauthenticated; bind to scrape-network interfaces)
                self._respond_traces_index(query)
                return
            if path.startswith("/traces/") and method == "GET":
                self._respond_trace(path[len("/traces/"):], query)
                return
            if path == "/plugins.json" and method == "GET":
                self._respond(200, srv.plugin_context.describe())
                return
            if path.startswith("/storage/"):
                srv.storage_auth(query)
                self._storage_route(srv, method, path, query)
                return
            auth = srv.authenticate(query, self.headers)
            status, payload = self._route(srv, method, path, query, auth)
            self._respond(status, payload)
        except _HttpError as e:
            if getattr(self, "_stream_started", False):
                self.close_connection = True
                return
            self._respond(e.status, e.payload)
        except Exception as e:
            logger.exception("unhandled error on %s %s", method, path)
            if getattr(self, "_stream_started", False):
                # mid-stream failure: a second status line would corrupt
                # the chunked framing — abort so the client sees a
                # truncated stream and raises
                self.close_connection = True
                return
            self._respond(500, {"message": str(e)})

    def _route(self, srv: EventServer, method: str, path: str,
               query: Dict[str, List[str]], auth: AuthData) -> Tuple[int, Any]:
        if path == "/events.json":
            if method == "POST":
                return srv.post_events(auth, self._body())
            if method == "GET":
                return srv.get_events(auth, query)
        elif path == "/batch/events.json":
            if method == "POST":
                return srv.post_batch(auth, self._body())
        elif path == "/stats.json" and method == "GET":
            return srv.get_stats(auth)
        elif path.startswith("/events/") and path.endswith(".json"):
            event_id = urllib.parse.unquote(
                path[len("/events/"):-len(".json")])
            if method == "GET":
                return srv.get_event(auth, event_id)
            if method == "DELETE":
                return srv.delete_event(auth, event_id)
        elif path.startswith("/webhooks/"):
            rest = path[len("/webhooks/"):]
            form = rest.endswith(".form")
            if rest.endswith(".json") or form:
                name = rest.rsplit(".", 1)[0]
                if method == "POST":
                    return srv.post_webhooks(
                        auth, name, form, self._body(),
                        self.headers.get("Content-Type", ""))
                if method == "GET":
                    return srv.get_webhooks(auth, name, form)
        elif path.startswith("/plugins/") and method == "GET":
            segments = [s for s in path.split("/") if s][1:]
            if len(segments) >= 2:
                ptype, pname, *args = segments
                ctx = srv.plugin_context
                reg = (ctx.input_blockers if ptype == "inputblocker"
                       else ctx.input_sniffers)
                plugin = reg.get(pname)
                if plugin is None:
                    return 404, {"message": f"plugin {pname} not found"}
                return 200, json.loads(
                    plugin.handle_rest(auth.app_id, auth.channel_id, args))
        return 404, {"message": "Not Found"}

    def _storage_route(self, srv: EventServer, method: str, path: str,
                       query: Dict[str, List[str]]) -> None:
        if path == "/storage/events.jsonl":
            if method == "GET":
                self._respond_chunked(200, srv.storage_stream(query))
                return
            if method == "POST":
                retried = bool(self.headers.get("X-Idempotency-Retry"))
                self._respond(*srv.storage_append(query, self._body(),
                                                  retried=retried))
                return
        elif path == "/storage/init.json" and method == "POST":
            self._respond(*srv.storage_init(query))
            return
        elif path == "/storage/remove.json" and method == "POST":
            self._respond(*srv.storage_remove(query))
            return
        elif path == "/storage/delete_until.json" and method == "POST":
            self._respond(*srv.storage_delete_until(query))
            return
        elif path == "/storage/aggregate.json" and method == "GET":
            self._respond(*srv.storage_aggregate(query))
            return
        elif path == "/storage/tail.json" and method in ("GET", "POST"):
            self._respond(*srv.storage_tail(
                query, self._request_body if method == "POST" else None))
            return
        elif path.startswith("/storage/events/") and path.endswith(".json"):
            # clients percent-encode ids with reserved characters
            event_id = urllib.parse.unquote(
                path[len("/storage/events/"):-len(".json")])
            if method == "GET":
                self._respond(*srv.storage_get_event(query, event_id))
                return
            if method == "DELETE":
                self._respond(*srv.storage_delete_event(query, event_id))
                return
        self._respond(404, {"message": "Not Found"})

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


def create_event_server(config: Optional[EventServerConfig] = None,
                        **kwargs) -> EventServer:
    """createEventServer parity (EventServer.scala:610-632)."""
    return EventServer(config, **kwargs)
