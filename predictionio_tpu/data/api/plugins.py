"""Event-server plugin SPI — input blockers and sniffers.

Parity target: ``data/.../api/EventServerPlugin.scala`` +
``EventServerPluginContext.scala``. The JVM ``ServiceLoader`` discovery is
replaced by an explicit registry (plus ``predictionio_tpu.plugins``
entry-point discovery when installed); the sniffer actor mailbox by
direct calls — sniffers must be cheap/non-blocking by contract.
"""

from __future__ import annotations

import abc
import logging
from typing import Dict, List, Optional

from predictionio_tpu.data.event import Event

INPUT_BLOCKER = "inputblocker"
INPUT_SNIFFER = "inputsniffer"


class EventInfo:
    """What a plugin sees per event (EventServerPlugin.scala:21-27)."""

    def __init__(self, app_id: int, channel_id: Optional[int], event: Event):
        self.app_id = app_id
        self.channel_id = channel_id
        self.event = event


class EventServerPlugin(abc.ABC):
    """An input blocker (may veto by raising) or sniffer (observe only)."""

    plugin_name: str = ""
    plugin_description: str = ""
    plugin_type: str = INPUT_SNIFFER

    @abc.abstractmethod
    def process(self, event_info: EventInfo,
                context: "EventServerPluginContext") -> None:
        """Blockers raise ValueError to reject the event; sniffers observe."""

    def handle_rest(self, app_id: int, channel_id: Optional[int],
                    args: List[str]) -> str:
        """GET /plugins/<type>/<name>/... hook (EventServerPlugin.scala:36-39)."""
        return "{}"


class EventServerPluginContext:
    """Registry of active plugins, split by type
    (EventServerPluginContext.scala:36-58)."""

    def __init__(self, plugins: Optional[List[EventServerPlugin]] = None,
                 logger: Optional[logging.Logger] = None):
        self.logger = logger or logging.getLogger("pio.eventserver.plugins")
        self.input_blockers: Dict[str, EventServerPlugin] = {}
        self.input_sniffers: Dict[str, EventServerPlugin] = {}
        for p in plugins or []:
            self.register(p)

    def register(self, plugin: EventServerPlugin) -> None:
        target = (self.input_blockers
                  if plugin.plugin_type == INPUT_BLOCKER
                  else self.input_sniffers)
        target[plugin.plugin_name] = plugin

    def describe(self) -> Dict[str, Dict[str, Dict[str, str]]]:
        """Wire shape of GET /plugins.json (EventServer.scala:155-174)."""
        def block(ps: Dict[str, EventServerPlugin]):
            return {
                n: {
                    "name": p.plugin_name,
                    "description": p.plugin_description,
                    "class": type(p).__module__ + "." + type(p).__qualname__,
                }
                for n, p in ps.items()
            }
        return {"plugins": {
            "inputblockers": block(self.input_blockers),
            "inputsniffers": block(self.input_sniffers),
        }}
