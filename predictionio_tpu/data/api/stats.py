"""Per-app ingestion counters with hourly rotation.

Parity targets: ``data/.../api/Stats.scala:48-79`` (counts keyed by
(appId, statusCode) and (appId, EntityTypesEvent)) and
``StatsActor.scala`` (long-lived + current-hour + previous-hour windows,
rotated on the hour). The actor mailbox is replaced by a lock — the
counters are tiny and the server is thread-per-request.
"""

from __future__ import annotations

import datetime as _dt
import threading
from collections import Counter
from typing import Any, Dict, Optional

from predictionio_tpu.data.event import Event

UTC = _dt.timezone.utc


def _ete(event: Event) -> tuple:
    """EntityTypesEvent key (Stats.scala:28-37)."""
    return (event.entity_type, event.target_entity_type, event.event)


class Stats:
    """One counting window (Stats.scala:48-79)."""

    def __init__(self, start_time: _dt.datetime):
        self.start_time = start_time
        self.end_time: Optional[_dt.datetime] = None
        self.status_code_count: Counter = Counter()   # (appId, status) -> n
        self.ete_count: Counter = Counter()           # (appId, ete) -> n

    def cutoff(self, end_time: _dt.datetime) -> None:
        self.end_time = end_time

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        self.status_code_count[(app_id, status_code)] += 1
        self.ete_count[(app_id, _ete(event))] += 1

    def snapshot(self, app_id: int) -> Dict[str, Any]:
        """StatsSnapshot as a JSON-ready dict (Stats.scala:40-45)."""
        return {
            "startTime": self.start_time.isoformat(),
            "endTime": self.end_time.isoformat() if self.end_time else None,
            "basic": [
                {
                    "entityType": k[1][0],
                    "targetEntityType": k[1][1],
                    "event": k[1][2],
                    "count": v,
                }
                for k, v in sorted(self.ete_count.items(), key=lambda x: -x[1])
                if k[0] == app_id
            ],
            "statusCode": [
                {"status": k[1], "count": v}
                for k, v in sorted(self.status_code_count.items())
                if k[0] == app_id
            ],
        }


def _hour_floor(t: _dt.datetime) -> _dt.datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class StatsKeeper:
    """Long-lived + hourly + previous-hour windows (StatsActor.scala:34-75)."""

    def __init__(self, now: Optional[_dt.datetime] = None):
        now = now or _dt.datetime.now(tz=UTC)
        self._lock = threading.Lock()
        self.long_live = Stats(now)
        self.hourly = Stats(_hour_floor(now))
        self.prev_hourly = Stats(_hour_floor(now) - _dt.timedelta(hours=1))
        self.prev_hourly.cutoff(self.hourly.start_time)

    def bookkeeping(self, app_id: int, status_code: int, event: Event,
                    now: Optional[_dt.datetime] = None) -> None:
        now = now or _dt.datetime.now(tz=UTC)
        current = _hour_floor(now)
        with self._lock:
            if current != self.hourly.start_time:
                self.prev_hourly = self.hourly
                self.prev_hourly.cutoff(current)
                self.hourly = Stats(current)
            self.hourly.update(app_id, status_code, event)
            self.long_live.update(app_id, status_code, event)

    def get(self, app_id: int) -> Dict[str, Any]:
        """Wire shape of GET /stats.json (EventServer.scala:441-467)."""
        with self._lock:
            return {
                "startTime": self.long_live.start_time.isoformat(),
                "hourly": self.hourly.snapshot(app_id),
                "prevHourly": self.prev_hourly.snapshot(app_id),
                "longLive": self.long_live.snapshot(app_id),
            }
