"""Event-collection REST layer (reference ``data/.../api/``)."""

from predictionio_tpu.data.api.event_server import (  # noqa: F401
    EventServer,
    EventServerConfig,
    create_event_server,
)
from predictionio_tpu.data.api.plugins import (  # noqa: F401
    EventServerPlugin,
    EventServerPluginContext,
)
from predictionio_tpu.data.api.stats import Stats, StatsKeeper  # noqa: F401
