"""DAO instrumentation + resilience: latency/error metrics, retries,
circuit breaking, and deterministic fault injection.

The storage registry wraps every event-store ``LEvents`` DAO it hands
out in :class:`DAOMetricsWrapper`, so all four event backends (memory,
sqlite, jsonlfs, resthttp) report
``pio_storage_op_seconds{backend,op,shard}`` and
``pio_storage_op_errors_total{backend,op,error,shard}`` without any
code in the backends themselves (``shard`` is empty for direct DAOs;
the fleet router stamps it on per-shard legs). Slow-path attribution rides the
request-scoped tracing contextvar: with debug logging on, every storage
op logs a record tagged with the ``X-Request-ID`` of the HTTP request
that caused it.

The wrapper is also the resilience chokepoint for LOCAL backends: each
op runs under the shared :class:`~predictionio_tpu.utils.resilience.
RetryPolicy` behind the backend's per-endpoint circuit breaker, with
the ``PIO_FAULTS`` injection hook (:mod:`predictionio_tpu.utils.faults`)
consulted immediately before the real call — so injected transients sit
INSIDE the retry loop and are masked exactly like real ones. Insert ops
pre-assign client-generated event ids before the first attempt, making
retried inserts idempotent on backends that dedup by event id
(``idempotent_event_writes``); backends that own their resilience
(resthttp: retries live in the wire, under the wire's breaker) declare
``self_resilient`` and are passed through untouched.

The wrapper is transparent: unknown attributes delegate to the wrapped
DAO (the jsonlfs raw-partition fast lane reads ``_dir``/``_parts``
through it), and code that needs the concrete backend type unwraps via
``unwrap()`` / the ``_wrapped`` attribute — ``isinstance`` on the
wrapper itself only sees :class:`~predictionio_tpu.data.storage.base.
LEvents`.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.utils import faults, metrics, resilience, tracing
from predictionio_tpu.utils.tracing import current_request_id

logger = logging.getLogger("pio.storage.ops")

# ops that mutate the store by APPENDING events — retried only when the
# events carry idempotency keys (event ids) a backend can dedup on
_WRITE_OPS = frozenset({"insert", "insert_batch", "append_raw_lines"})

# passthrough attributes that still deserve timing (optional per backend);
# the tail-read trio (find_since/tail_cursor/tail_watermark) is declared
# on base.LEvents so it never reaches __getattr__ — it gets explicit
# timed+resilient delegation below instead
_EXTRA_TIMED_OPS = ("append_raw_lines",)


def unwrap(dao: Any) -> Any:
    """The concrete DAO behind a (possibly) wrapped one."""
    return getattr(dao, "_wrapped", dao)


class _TimedIterator:
    """Wraps a lazy ``find`` result so the recorded duration covers the
    scan, not just generator creation; abandoning the iterator records
    nothing (there is no completed op to account). ``fail`` (optional)
    accounts a mid-iteration error — the scan IS the op, so a backend
    dying partway through must register as the op failing."""

    __slots__ = ("_it", "_done", "_fail")

    def __init__(self, it: Iterator, done: Callable[[], None],
                 fail: Optional[Callable[[BaseException], None]] = None):
        self._it = iter(it)
        self._done = done
        self._fail = fail

    def __iter__(self) -> "_TimedIterator":
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            done, self._done = self._done, lambda: None
            done()
            raise
        except BaseException as e:
            fail, self._fail = self._fail, None
            self._done = lambda: None
            if fail is not None:
                fail(e)
            raise


class DAOMetricsWrapper(base.LEvents):
    """Time + error-count every event-store op against the registry."""

    def __init__(self, wrapped: base.LEvents,
                 backend: Optional[str] = None, shard: str = ""):
        self._wrapped = wrapped
        self.metrics_backend = backend or getattr(
            wrapped, "metrics_backend", type(wrapped).__name__)
        # empty for direct DAOs; the fleet router sets the shard index
        # on the per-shard clients it wraps so fan-out legs are
        # attributable in pio_storage_op_seconds{shard=...}
        self.metrics_shard = shard or getattr(
            wrapped, "metrics_shard", "")
        # resilience surface: the endpoint names the availability
        # domain (a wire URL for resthttp, the backend name locally)
        self.resilience_endpoint = getattr(
            wrapped, "resilience_endpoint", None) or self.metrics_backend
        self._self_resilient = bool(
            getattr(wrapped, "self_resilient", False))
        self._idempotent_writes = bool(
            getattr(wrapped, "idempotent_event_writes", False))
        self._breaker = resilience.breaker_for(self.resilience_endpoint)
        self._policy = resilience.RetryPolicy.from_env()

    def unwrap(self) -> base.LEvents:
        return self._wrapped

    # -- resilience -------------------------------------------------------
    def _attempt(self, op: str, fn: Callable, args: tuple, kwargs: dict):
        """One attempt: consult the fault injector, honor a torn-write
        directive (execute HALF the write, then fail ambiguously —
        the mid-write-crash shape), then run the real op."""
        directive = faults.maybe_fault(self.metrics_backend, op)
        if directive is not None:
            if op in ("insert_batch", "append_raw_lines") and args:
                seq = list(args[0])
                half = seq[:len(seq) // 2]
                if half:
                    fn(half, *args[1:], **kwargs)
            raise directive.error()
        return fn(*args, **kwargs)

    def _call_resilient(self, op: str, fn: Callable,
                        args: tuple, kwargs: dict,
                        defer_success: bool = False):
        """Breaker + retry + fault hook around one DAO op. Insert ops
        get their event ids assigned BEFORE the first attempt so every
        retry replays the same ids (the idempotency keys backends
        dedup on). ``defer_success`` skips the breaker's success mark —
        for lazy ops (``find`` returns a generator whose scan has not
        run yet) the CALLER records the outcome when iteration ends, so
        generator creation cannot masquerade as a healthy read and keep
        resetting the breaker's consecutive-failure count."""
        if self._self_resilient:
            return fn(*args, **kwargs)
        if not resilience.enabled():
            # kill switch drops retries + breaker, NOT fault injection
            # (the chaos bench measures the unmasked error rate here)
            return self._attempt(op, fn, args, kwargs)
        idempotent = op not in _WRITE_OPS or self._idempotent_writes
        if op in ("insert", "insert_batch") and args:
            from predictionio_tpu.data.event import new_event_id

            if op == "insert":
                ev = args[0]
                if hasattr(ev, "with_id") and \
                        not getattr(ev, "event_id", None):
                    args = (ev.with_id(new_event_id()),) + args[1:]
            else:
                seq = list(args[0])
                if all(hasattr(e, "with_id") for e in seq):
                    seq = [e if getattr(e, "event_id", None)
                           else e.with_id(new_event_id()) for e in seq]
                args = (seq,) + args[1:]
        def on_retry(attempt: int, exc: BaseException,
                     delay: float) -> None:
            metrics.STORAGE_RETRIES.inc(backend=self.metrics_backend,
                                        op=op)
            logger.debug("storage %s.%s retry %d in %.3fs after %r",
                         self.metrics_backend, op, attempt + 1, delay,
                         exc)

        return base.run_guarded(
            self._breaker, self._policy,
            lambda attempt: self._attempt(op, fn, args, kwargs),
            idempotent=idempotent, on_retry=on_retry,
            defer_success=defer_success)

    # -- accounting -------------------------------------------------------
    def _record(self, op: str, t0: float,
                error: Optional[BaseException] = None) -> None:
        took = time.perf_counter() - t0
        backend = self.metrics_backend
        shard = self.metrics_shard
        if error is not None:
            metrics.STORAGE_OP_ERRORS.inc(
                backend=backend, op=op, error=type(error).__name__,
                shard=shard)
        else:
            metrics.STORAGE_OP_LATENCY.observe(
                took, backend=backend, op=op, shard=shard)
        if logger.isEnabledFor(logging.DEBUG):
            rid = current_request_id() or "-"
            logger.debug("storage %s.%s %.6fs rid=%s%s", backend, op, took,
                         rid, f" error={error!r}" if error else "")

    def _observe(self, op: str, fn: Callable, *args, **kwargs):
        # trace spans are independent of the metrics switch: an active
        # trace records storage-op spans even with metrics off, and
        # metrics keep counting when tracing is killed
        sp, tok = tracing.begin_span(
            f"storage.{self.metrics_backend}.{op}")
        record = metrics.REGISTRY.enabled
        if not record and sp is None:
            return self._call_resilient(op, fn, args, kwargs)
        t0 = time.perf_counter()
        try:
            result = self._call_resilient(op, fn, args, kwargs)
        except BaseException as e:
            if record:
                self._record(op, t0, error=e)
            tracing.finish_span(sp, tok, error=e)
            raise
        if record:
            self._record(op, t0)
        tracing.finish_span(sp, tok)
        return result

    # -- LEvents contract -------------------------------------------------
    def init(self, app_id, channel_id=None) -> bool:
        return self._observe("init", self._wrapped.init, app_id, channel_id)

    def remove(self, app_id, channel_id=None) -> bool:
        return self._observe("remove", self._wrapped.remove, app_id,
                             channel_id)

    def close(self) -> None:
        self._wrapped.close()

    def insert(self, event, app_id, channel_id=None) -> str:
        return self._observe("insert", self._wrapped.insert, event, app_id,
                             channel_id)

    def insert_batch(self, events: Iterable, app_id, channel_id=None):
        return self._observe("insert_batch", self._wrapped.insert_batch,
                             events, app_id, channel_id)

    def get(self, event_id, app_id, channel_id=None):
        return self._observe("get", self._wrapped.get, event_id, app_id,
                             channel_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        return self._observe("delete", self._wrapped.delete, event_id,
                             app_id, channel_id)

    def delete_until(self, app_id, until_time, channel_id=None) -> int:
        return self._observe("delete_until", self._wrapped.delete_until,
                             app_id, until_time, channel_id)

    def find(self, app_id, channel_id=None, **kwargs):
        # the span is finished by the iterator-exhausted callback (the
        # scan IS the op), so it must not rebind the context var — the
        # consuming code in between is not "inside the scan"
        sp, _ = tracing.begin_span(
            f"storage.{self.metrics_backend}.find", set_current=False)
        record = metrics.REGISTRY.enabled
        # the retry covers find() CREATION (local backends with lazy
        # scans return a generator from it); consuming the returned
        # iterator is not replayable — a mid-iteration failure
        # propagates — but it IS the scan, so the breaker's verdict
        # (success or failure) is deferred to the iterator's end
        deferred = not self._self_resilient and resilience.enabled()
        if not record and sp is None and not deferred:
            return self._call_resilient(
                "find", self._wrapped.find, (app_id, channel_id), kwargs)
        t0 = time.perf_counter()
        try:
            it = self._call_resilient(
                "find", self._wrapped.find, (app_id, channel_id), kwargs,
                defer_success=deferred)
        except BaseException as e:
            if record:
                self._record("find", t0, error=e)
            tracing.finish_span(sp, error=e)
            raise

        def done() -> None:
            if deferred:
                self._breaker.record_success()
            if record:
                self._record("find", t0)
            tracing.finish_span(sp)

        def fail(e: BaseException) -> None:
            if deferred:
                self._breaker.record_failure(e)
            if record:
                self._record("find", t0, error=e)
            tracing.finish_span(sp, error=e)
        return _TimedIterator(it, done, fail)

    # the tail-read trio is defined on base.LEvents (so __getattr__ never
    # fires for it) — delegate explicitly, timed + resilient like any op
    def find_since(self, app_id, channel_id=None, cursor=None, limit=None):
        return self._observe("find_since", self._wrapped.find_since,
                             app_id, channel_id, cursor=cursor, limit=limit)

    def tail_cursor(self, app_id, channel_id=None):
        return self._observe("tail_cursor", self._wrapped.tail_cursor,
                             app_id, channel_id)

    def tail_watermark(self, app_id, channel_id=None):
        return self._observe("tail_watermark", self._wrapped.tail_watermark,
                             app_id, channel_id)

    def materialized_aggregate(self, app_id, entity_type, channel_id=None):
        return self._observe(
            "materialized_aggregate", self._wrapped.materialized_aggregate,
            app_id, entity_type, channel_id)

    def aggregate_properties_replay(self, app_id, entity_type,
                                    channel_id=None, start_time=None,
                                    until_time=None, required=None):
        return self._observe(
            "aggregate_replay", self._wrapped.aggregate_properties_replay,
            app_id, entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)

    def aggregate_properties(self, app_id, entity_type, channel_id=None,
                             start_time=None, until_time=None,
                             required=None):
        # delegate straight through: the wrapped DAO's own
        # aggregate_properties does the hit/replay accounting, and its
        # inner materialized/replay calls are the ones worth timing
        return self._observe(
            "aggregate", self._wrapped.aggregate_properties,
            app_id, entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)

    # -- transparency -----------------------------------------------------
    def __getattr__(self, name: str):
        # only called for attributes NOT defined above (Python attribute
        # protocol), so the LEvents surface stays timed and everything
        # else (backend internals, shutdown, _w, _dir, ...) delegates
        if name == "_wrapped":  # guard: never recurse before __init__ ran
            raise AttributeError(name)
        attr = getattr(self._wrapped, name)
        if name in _EXTRA_TIMED_OPS and callable(attr):
            def timed(*args, **kwargs):
                return self._observe(name, attr, *args, **kwargs)
            return timed
        return attr

    def __repr__(self) -> str:
        return f"DAOMetricsWrapper({self._wrapped!r})"
