"""DAO instrumentation: per-backend / per-op latency + error counters.

The storage registry wraps every event-store ``LEvents`` DAO it hands
out in :class:`DAOMetricsWrapper`, so all four event backends (memory,
sqlite, jsonlfs, resthttp) report ``pio_storage_op_seconds{backend,op}``
and ``pio_storage_op_errors_total{backend,op,error}`` without any code
in the backends themselves. Slow-path attribution rides the
request-scoped tracing contextvar: with debug logging on, every storage
op logs a record tagged with the ``X-Request-ID`` of the HTTP request
that caused it.

The wrapper is transparent: unknown attributes delegate to the wrapped
DAO (the jsonlfs raw-partition fast lane reads ``_dir``/``_parts``
through it), and code that needs the concrete backend type unwraps via
``unwrap()`` / the ``_wrapped`` attribute — ``isinstance`` on the
wrapper itself only sees :class:`~predictionio_tpu.data.storage.base.
LEvents`.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.utils import metrics, tracing
from predictionio_tpu.utils.tracing import current_request_id

logger = logging.getLogger("pio.storage.ops")

# passthrough attributes that still deserve timing (optional per backend)
_EXTRA_TIMED_OPS = ("append_raw_lines",)


def unwrap(dao: Any) -> Any:
    """The concrete DAO behind a (possibly) wrapped one."""
    return getattr(dao, "_wrapped", dao)


class _TimedIterator:
    """Wraps a lazy ``find`` result so the recorded duration covers the
    scan, not just generator creation; abandoning the iterator records
    nothing (there is no completed op to account)."""

    __slots__ = ("_it", "_done")

    def __init__(self, it: Iterator, done: Callable[[], None]):
        self._it = iter(it)
        self._done = done

    def __iter__(self) -> "_TimedIterator":
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            done, self._done = self._done, lambda: None
            done()
            raise


class DAOMetricsWrapper(base.LEvents):
    """Time + error-count every event-store op against the registry."""

    def __init__(self, wrapped: base.LEvents,
                 backend: Optional[str] = None):
        self._wrapped = wrapped
        self.metrics_backend = backend or getattr(
            wrapped, "metrics_backend", type(wrapped).__name__)

    def unwrap(self) -> base.LEvents:
        return self._wrapped

    # -- accounting -------------------------------------------------------
    def _record(self, op: str, t0: float,
                error: Optional[BaseException] = None) -> None:
        took = time.perf_counter() - t0
        backend = self.metrics_backend
        if error is not None:
            metrics.STORAGE_OP_ERRORS.inc(
                backend=backend, op=op, error=type(error).__name__)
        else:
            metrics.STORAGE_OP_LATENCY.observe(took, backend=backend, op=op)
        if logger.isEnabledFor(logging.DEBUG):
            rid = current_request_id() or "-"
            logger.debug("storage %s.%s %.6fs rid=%s%s", backend, op, took,
                         rid, f" error={error!r}" if error else "")

    def _observe(self, op: str, fn: Callable, *args, **kwargs):
        # trace spans are independent of the metrics switch: an active
        # trace records storage-op spans even with metrics off, and
        # metrics keep counting when tracing is killed
        sp, tok = tracing.begin_span(
            f"storage.{self.metrics_backend}.{op}")
        record = metrics.REGISTRY.enabled
        if not record and sp is None:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            result = fn(*args, **kwargs)
        except BaseException as e:
            if record:
                self._record(op, t0, error=e)
            tracing.finish_span(sp, tok, error=e)
            raise
        if record:
            self._record(op, t0)
        tracing.finish_span(sp, tok)
        return result

    # -- LEvents contract -------------------------------------------------
    def init(self, app_id, channel_id=None) -> bool:
        return self._observe("init", self._wrapped.init, app_id, channel_id)

    def remove(self, app_id, channel_id=None) -> bool:
        return self._observe("remove", self._wrapped.remove, app_id,
                             channel_id)

    def close(self) -> None:
        self._wrapped.close()

    def insert(self, event, app_id, channel_id=None) -> str:
        return self._observe("insert", self._wrapped.insert, event, app_id,
                             channel_id)

    def insert_batch(self, events: Iterable, app_id, channel_id=None):
        return self._observe("insert_batch", self._wrapped.insert_batch,
                             events, app_id, channel_id)

    def get(self, event_id, app_id, channel_id=None):
        return self._observe("get", self._wrapped.get, event_id, app_id,
                             channel_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        return self._observe("delete", self._wrapped.delete, event_id,
                             app_id, channel_id)

    def delete_until(self, app_id, until_time, channel_id=None) -> int:
        return self._observe("delete_until", self._wrapped.delete_until,
                             app_id, until_time, channel_id)

    def find(self, app_id, channel_id=None, **kwargs):
        # the span is finished by the iterator-exhausted callback (the
        # scan IS the op), so it must not rebind the context var — the
        # consuming code in between is not "inside the scan"
        sp, _ = tracing.begin_span(
            f"storage.{self.metrics_backend}.find", set_current=False)
        record = metrics.REGISTRY.enabled
        if not record and sp is None:
            return self._wrapped.find(app_id, channel_id, **kwargs)
        t0 = time.perf_counter()
        try:
            it = self._wrapped.find(app_id, channel_id, **kwargs)
        except BaseException as e:
            if record:
                self._record("find", t0, error=e)
            tracing.finish_span(sp, error=e)
            raise

        def done() -> None:
            if record:
                self._record("find", t0)
            tracing.finish_span(sp)
        return _TimedIterator(it, done)

    def materialized_aggregate(self, app_id, entity_type, channel_id=None):
        return self._observe(
            "materialized_aggregate", self._wrapped.materialized_aggregate,
            app_id, entity_type, channel_id)

    def aggregate_properties_replay(self, app_id, entity_type,
                                    channel_id=None, start_time=None,
                                    until_time=None, required=None):
        return self._observe(
            "aggregate_replay", self._wrapped.aggregate_properties_replay,
            app_id, entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)

    def aggregate_properties(self, app_id, entity_type, channel_id=None,
                             start_time=None, until_time=None,
                             required=None):
        # delegate straight through: the wrapped DAO's own
        # aggregate_properties does the hit/replay accounting, and its
        # inner materialized/replay calls are the ones worth timing
        return self._observe(
            "aggregate", self._wrapped.aggregate_properties,
            app_id, entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)

    # -- transparency -----------------------------------------------------
    def __getattr__(self, name: str):
        # only called for attributes NOT defined above (Python attribute
        # protocol), so the LEvents surface stays timed and everything
        # else (backend internals, shutdown, _w, _dir, ...) delegates
        if name == "_wrapped":  # guard: never recurse before __init__ ran
            raise AttributeError(name)
        attr = getattr(self._wrapped, name)
        if name in _EXTRA_TIMED_OPS and callable(attr):
            def timed(*args, **kwargs):
                return self._observe(name, attr, *args, **kwargs)
            return timed
        return attr

    def __repr__(self) -> str:
        return f"DAOMetricsWrapper({self._wrapped!r})"
