"""Storage registry: env-var-driven backend discovery.

Parity target: reference ``Storage.scala`` —

- sources from ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ per-type config keys,
  Storage.scala:124-137); our types: ``memory``, ``sqlite`` (config key
  ``PATH``).
- repositories from ``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,
  MODELDATA}_{NAME,SOURCE}`` (Storage.scala:144-193).
- accessors ``get_levents`` / ``get_pevents`` / ``get_metadata_*`` /
  ``get_model_data_models`` (Storage.scala:360-402).
- ``verify_all_data_objects`` for ``pio status`` (Storage.scala:335-358).

Unlike the reference there is no classpath reflection: backends register in
``BACKENDS`` and unknown types raise ``StorageError`` with the known set.

Defaults (no env set): a single sqlite source at ``$PIO_STORAGE_PATH`` or
``./.pio_store/pio.db`` serving all three repositories — the zero-service
bring-up the reference never had.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import StorageError

REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")

# backend type -> DAO kind -> "module:Class"
BACKENDS: Dict[str, Dict[str, str]] = {
    "memory": {
        "LEvents": "predictionio_tpu.data.storage.memory:MemLEvents",
        "PEvents": "predictionio_tpu.data.storage.memory:MemLEvents",  # wrapped
        "Apps": "predictionio_tpu.data.storage.memory:MemApps",
        "AccessKeys": "predictionio_tpu.data.storage.memory:MemAccessKeys",
        "Channels": "predictionio_tpu.data.storage.memory:MemChannels",
        "EngineInstances": "predictionio_tpu.data.storage.memory:MemEngineInstances",
        "EvaluationInstances": "predictionio_tpu.data.storage.memory:MemEvaluationInstances",
        "Models": "predictionio_tpu.data.storage.memory:MemModels",
    },
    "sqlite": {
        "LEvents": "predictionio_tpu.data.storage.sqlite:SqliteLEvents",
        "PEvents": "predictionio_tpu.data.storage.sqlite:SqlitePEvents",
        "Apps": "predictionio_tpu.data.storage.sqlite:SqliteApps",
        "AccessKeys": "predictionio_tpu.data.storage.sqlite:SqliteAccessKeys",
        "Channels": "predictionio_tpu.data.storage.sqlite:SqliteChannels",
        "EngineInstances": "predictionio_tpu.data.storage.sqlite:SqliteEngineInstances",
        "EvaluationInstances": "predictionio_tpu.data.storage.sqlite:SqliteEvaluationInstances",
        "Models": "predictionio_tpu.data.storage.sqlite:SqliteModels",
    },
    # MODELDATA-only filesystem blob store (LocalFSModels.scala analog)
    "localfs": {
        "Models": "predictionio_tpu.data.storage.localfs:LocalFSModels",
    },
    # EVENTDATA-only partitioned JSONL store — the scale-ingest backend
    # (JDBCPEvents.scala:31-100 / HBPEvents.scala:83-89 analog); config
    # keys: PATH, PART_MAX_EVENTS
    "jsonlfs": {
        "LEvents": "predictionio_tpu.data.storage.jsonlfs:JsonlFsLEvents",
        "PEvents": "predictionio_tpu.data.storage.jsonlfs:JsonlFsPEvents",
    },
    # EVENTDATA-only networked backend: DAOs speak HTTP to a remote
    # event server's /storage wire (the Storage.scala:360-391 remote-DAO
    # architecture — train on one machine, store on another); config
    # keys: URL, SERVICE_KEY, TIMEOUT
    "resthttp": {
        "LEvents": "predictionio_tpu.data.storage.resthttp:RestLEvents",
        "PEvents": "predictionio_tpu.data.storage.resthttp:RestPEvents",
    },
    # EVENTDATA-only consistent-hash router over N event-server shards:
    # writes fan out by entity key, reads scatter-gather and merge;
    # config keys: URLS (comma-separated shard URLs), SERVICE_KEY,
    # VIRTUAL_NODES, plus resthttp wire keys applied per shard
    "fleet": {
        "LEvents": "predictionio_tpu.fleet.router:FleetLEvents",
        "PEvents": "predictionio_tpu.fleet.router:FleetPEvents",
    },
}


def _load(spec: str):
    mod_name, cls_name = spec.split(":")
    import importlib
    return getattr(importlib.import_module(mod_name), cls_name)


def default_storage_path() -> str:
    p = os.environ.get("PIO_STORAGE_PATH")
    if p:
        return p
    d = os.path.join(os.getcwd(), ".pio_store")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "pio.db")


class StorageConfig:
    """Parsed source/repository configuration."""

    def __init__(self, sources: Dict[str, Dict[str, Any]],
                 repositories: Dict[str, str]):
        self.sources = sources          # name -> {"type": ..., **config}
        self.repositories = repositories  # repo -> source name

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "StorageConfig":
        env = dict(os.environ if env is None else env)
        sources: Dict[str, Dict[str, Any]] = {}
        prefix = "PIO_STORAGE_SOURCES_"
        for key, val in env.items():
            if key.startswith(prefix) and key.endswith("_TYPE"):
                name = key[len(prefix):-len("_TYPE")]
                cfg: Dict[str, Any] = {"type": val.lower()}
                srcpfx = f"{prefix}{name}_"
                for k2, v2 in env.items():
                    if k2.startswith(srcpfx) and k2 != key:
                        cfg[k2[len(srcpfx):].lower()] = v2
                sources[name] = cfg
        repositories: Dict[str, str] = {}
        for repo in REPOSITORIES:
            src = env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
            if src:
                repositories[repo] = src
        if not sources:
            sources["DEFAULT"] = {"type": "sqlite",
                                  "path": default_storage_path()}
        unbound = [r for r in REPOSITORIES if r not in repositories]
        if unbound:
            if len(sources) == 1:
                only = next(iter(sources))
                for repo in unbound:
                    repositories[repo] = only
            else:
                # Never guess among multiple sources — data could silently
                # land in the wrong backend (cf. Storage.scala:144-193,
                # which requires explicit repository bindings).
                raise StorageError(
                    f"Repositories {unbound} have no "
                    f"PIO_STORAGE_REPOSITORIES_<REPO>_SOURCE set and more "
                    f"than one source is defined ({sorted(sources)}); bind "
                    f"them explicitly.")
        for repo, src in repositories.items():
            if src not in sources:
                raise StorageError(
                    f"Repository {repo} references undefined source {src}. "
                    f"Defined sources: {sorted(sources)}")
        for name, cfg in sources.items():
            if cfg["type"] not in BACKENDS:
                raise StorageError(
                    f"Storage source {name} has unknown type {cfg['type']!r}. "
                    f"Known types: {sorted(BACKENDS)}")
        return cls(sources, repositories)


class StorageRegistry:
    """Instantiates and caches DAOs per (source, kind)."""

    def __init__(self, config: Optional[StorageConfig] = None):
        self._config = config
        self._cache: Dict[tuple, Any] = {}
        self._lock = threading.RLock()

    @property
    def config(self) -> StorageConfig:
        if self._config is None:
            self._config = StorageConfig.from_env()
        return self._config

    def reset(self, config: Optional[StorageConfig] = None) -> None:
        """Swap config and tear down DAOs this registry created.

        Teardown is backend-agnostic: any cached DAO exposing ``shutdown()``
        (e.g. the sqlite DAOs' client teardown) is shut down; DAOs created
        outside this registry are untouched.
        """
        with self._lock:
            old = list(self._cache.values())
            self._config = config
            self._cache = {}
            for dao in old:
                shutdown = getattr(dao, "shutdown", None)
                if callable(shutdown):
                    shutdown()

    def _dao(self, repo: str, kind: str):
        source = self.config.repositories[repo]
        cfg = self.config.sources[source]
        key = (source, kind)
        with self._lock:
            if key not in self._cache:
                kinds = BACKENDS[cfg["type"]]
                if kind not in kinds:
                    raise StorageError(
                        f"Storage source {source} (type {cfg['type']}) does "
                        f"not support {kind}; it provides {sorted(kinds)}. "
                        f"Bind repository {repo} to a different source.")
                spec = kinds[kind]
                if kind == "PEvents" and spec == BACKENDS[cfg["type"]]["LEvents"]:
                    # Backend has no dedicated PEvents: wrap the SHARED
                    # LEvents DAO so both views see the same state.
                    inst = base.LEventsBackedPEvents(self._dao(repo, "LEvents"))
                else:
                    inst = _load(spec)(cfg)
                    if isinstance(inst, base.LEvents) and kind == "PEvents":
                        inst = base.LEventsBackedPEvents(inst)
                if kind == "LEvents" and isinstance(inst, base.LEvents):
                    # every event-store DAO the registry hands out reports
                    # pio_storage_op_* metrics; code needing the concrete
                    # backend type unwraps via observed.unwrap()
                    from predictionio_tpu.data.storage.observed import (
                        DAOMetricsWrapper,
                    )
                    inst = DAOMetricsWrapper(inst, backend=cfg["type"])
                self._cache[key] = inst
            return self._cache[key]

    # -- accessors (Storage.scala:360-402) --------------------------------
    def get_levents(self) -> base.LEvents:
        return self._dao("EVENTDATA", "LEvents")

    def get_pevents(self) -> base.PEvents:
        return self._dao("EVENTDATA", "PEvents")

    def get_metadata_apps(self) -> base.Apps:
        return self._dao("METADATA", "Apps")

    def get_metadata_access_keys(self) -> base.AccessKeys:
        return self._dao("METADATA", "AccessKeys")

    def get_metadata_channels(self) -> base.Channels:
        return self._dao("METADATA", "Channels")

    def get_metadata_engine_instances(self) -> base.EngineInstances:
        return self._dao("METADATA", "EngineInstances")

    def get_metadata_evaluation_instances(self) -> base.EvaluationInstances:
        return self._dao("METADATA", "EvaluationInstances")

    def get_model_data_models(self) -> base.Models:
        return self._dao("MODELDATA", "Models")

    def verify_all_data_objects(self) -> None:
        """pio-status storage check (Storage.scala:335-358): touch every
        DAO, then run an insert/get/delete round-trip on the event store."""
        self.get_metadata_apps().get_all()
        self.get_metadata_access_keys().get_all()
        self.get_metadata_channels().get_by_appid(0)
        self.get_metadata_engine_instances().get_all()
        self.get_metadata_evaluation_instances().get_all()
        self.get_model_data_models().get("__status_check__")
        levents = self.get_levents()
        levents.init(0)
        from predictionio_tpu.data.event import Event
        eid = levents.insert(
            Event(event="$set", entity_type="status_check", entity_id="check",
                  properties={"ok": True}), 0)
        if levents.get(eid, 0) is None:
            raise StorageError(
                "Event store round-trip failed: inserted test event "
                "could not be read back")
        levents.delete(eid, 0)
        levents.remove(0)


_registry = StorageRegistry()


def registry() -> StorageRegistry:
    return _registry


def reset(config: Optional[StorageConfig] = None) -> None:
    """Reset the process-global registry (tests / config reload)."""
    _registry.reset(config)


def get_levents() -> base.LEvents:
    return _registry.get_levents()


def get_pevents() -> base.PEvents:
    return _registry.get_pevents()


def get_metadata_apps() -> base.Apps:
    return _registry.get_metadata_apps()


def get_metadata_access_keys() -> base.AccessKeys:
    return _registry.get_metadata_access_keys()


def get_metadata_channels() -> base.Channels:
    return _registry.get_metadata_channels()


def get_metadata_engine_instances() -> base.EngineInstances:
    return _registry.get_metadata_engine_instances()


def get_metadata_evaluation_instances() -> base.EvaluationInstances:
    return _registry.get_metadata_evaluation_instances()


def get_model_data_models() -> base.Models:
    return _registry.get_model_data_models()


def verify_all_data_objects() -> None:
    _registry.verify_all_data_objects()
