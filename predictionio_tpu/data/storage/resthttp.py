"""REST-client event storage backend (``resthttp``).

The networked storage lane: LEvents/PEvents DAOs that speak HTTP to a
running event server's ``/storage/*`` wire, so an engine trains against
an event store living on ANOTHER machine/process — the defining
property of the reference's storage layer, where ``Storage.scala:360-391``
hands out DAOs for remote HBase/ES/JDBC services and training scans
regions over the network (``HBPEvents.scala:83-89``,
``JDBCPEvents.scala:31-100``). No DB services exist in this environment;
the event server IS the service, and the wire format is the same
event-JSONL every other component speaks.

- Typed CRUD/find ride ``/storage/events.json[l]`` (server-side
  filtering for ``find``).
- Bulk training reads (``find_columnar_blocks``) fetch the UNFILTERED
  raw stream — for a jsonlfs-backed server that is partition bytes with
  zero server-side parsing — and decode client-side with the native C++
  codec (``jsonlfs.decode_jsonl_events``), filters applied over
  dictionary codes. The network ships bytes; the training host pays the
  decode, exactly like a remote HBase scan.

Config (``PIO_STORAGE_SOURCES_<NAME>_{URL,SERVICE_KEY,TIMEOUT,
CA_FILE,INSECURE_SKIP_VERIFY}``): ``url`` e.g.
``http(s)://eventhost:7070``; ``service_key`` must match the server's
``--service-key``; for ``https`` URLs ``ca_file`` pins the server's
(typically self-signed) certificate; ``verify_hostname=false`` for
IP-only deployments with CN-only certs. Only the event DAOs exist — configure this
source for EVENTDATA and keep METADATA/MODELDATA local (the registry
raises per-kind capability errors otherwise).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterable, List, Optional, Sequence

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import (
    Event,
    new_event_id,
    validate_event,
)
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import UNSET, StorageError
from predictionio_tpu.utils.tracing import outbound_context_headers, span


class _Wire:
    """Shared HTTP plumbing for the storage wire.

    For an ``https://`` URL, ``ca_file`` pins the server certificate
    (the usual self-signed deployment); ``insecure_skip_verify`` (bool)
    disables verification entirely — test rigs only."""

    def __init__(self, config: Optional[dict] = None):
        cfg = config or {}
        self.url = (cfg.get("url") or "http://127.0.0.1:7070").rstrip("/")
        self.service_key = cfg.get("service_key") or ""
        self.timeout = float(cfg.get("timeout", 60))
        self._ssl_ctx = None
        if self.url.startswith("https://"):
            import ssl

            ca = cfg.get("ca_file")
            skip = str(cfg.get("insecure_skip_verify", "")
                       ).strip().lower() in ("1", "true", "yes")
            ctx = ssl.create_default_context(cafile=ca or None)
            # hostname verification stays ON by default even with a
            # pinned ca_file (a CA bundle signs many hosts); IP-only
            # deployments with CN-only self-signed certs opt out via
            # verify_hostname=false
            if str(cfg.get("verify_hostname", "")
                   ).strip().lower() in ("0", "false", "no"):
                ctx.check_hostname = False
            if skip:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl_ctx = ctx

    def _open(self, req):
        return urllib.request.urlopen(req, timeout=self.timeout,
                                      context=self._ssl_ctx)

    def _full(self, path: str, params: dict) -> str:
        q = {"serviceKey": self.service_key}
        for k, v in params.items():
            if v is not None:
                q[k] = v
        return f"{self.url}{path}?" + urllib.parse.urlencode(q, doseq=True)

    @staticmethod
    def _inject_context(req) -> None:
        """Forward the caller's observability context on EVERY wire
        call: the contextvar request id (so the server's storage-op
        records join the originating request) and the W3C traceparent
        (so the server's spans join the originating trace). Must run
        INSIDE the wire span, which is then the remote spans' parent."""
        for name, value in outbound_context_headers().items():
            req.add_header(name, value)

    def call(self, method: str, path: str, params: dict,
             body: Optional[bytes] = None, ok=(200,)):
        with span(f"resthttp {method} {path}",
                  attributes={"url": self.url}):
            req = urllib.request.Request(self._full(path, params),
                                         data=body, method=method)
            if body is not None:
                req.add_header("Content-Type", "application/x-jsonlines")
            self._inject_context(req)
            try:
                with self._open(req) as resp:
                    payload = json.loads(resp.read().decode("utf-8"))
                    status = resp.status
            except urllib.error.HTTPError as e:
                status = e.code
                try:
                    payload = json.loads(e.read().decode("utf-8"))
                except Exception:
                    payload = {"message": str(e)}
            except OSError as e:  # URLError is an OSError subclass
                # also covers connection-level failures urlopen does not
                # wrap (e.g. RemoteDisconnected from plain HTTP hitting a
                # TLS listener)
                raise StorageError(
                    f"event server unreachable at {self.url}: {e}") from e
            if status not in ok:
                raise StorageError(
                    f"{method} {path} -> {status}: "
                    f"{payload.get('message', payload)}")
            return status, payload

    def stream(self, params: dict):
        """GET /storage/events.jsonl as a raw byte-chunk iterator. The
        wire span covers the connect + response headers (the streamed
        read itself is accounted by the caller's storage.find span)."""
        try:
            with span("resthttp GET /storage/events.jsonl",
                      attributes={"url": self.url, "streaming": True}):
                req = urllib.request.Request(
                    self._full("/storage/events.jsonl", params),
                    method="GET")
                self._inject_context(req)
                resp = self._open(req)
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read().decode("utf-8")).get("message")
            except Exception:
                msg = str(e)
            raise StorageError(
                f"GET /storage/events.jsonl -> {e.code}: {msg}") from e
        except OSError as e:  # URLError is an OSError subclass
            raise StorageError(
                f"event server unreachable at {self.url}: {e}") from e

        def chunks():
            with resp:
                while True:
                    c = resp.read(1 << 22)
                    if not c:
                        break
                    yield c
        return chunks()


def _scope(app_id: int, channel_id: Optional[int]) -> dict:
    p = {"appId": int(app_id)}
    if channel_id is not None:
        p["channelId"] = int(channel_id)
    return p


class RestLEvents(base.LEvents):
    """LEvents client over the event server's storage wire."""

    metrics_backend = "resthttp"

    def __init__(self, config: Optional[dict] = None):
        self._w = _Wire(config)

    # -- lifecycle --------------------------------------------------------
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        _, p = self._w.call("POST", "/storage/init.json",
                            _scope(app_id, channel_id))
        return bool(p.get("ok"))

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        _, p = self._w.call("POST", "/storage/remove.json",
                            _scope(app_id, channel_id))
        return bool(p.get("ok"))

    def close(self) -> None:
        pass

    # -- writes -----------------------------------------------------------
    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Iterable[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        evs = list(events)
        for e in evs:
            validate_event(e)
        ids = [e.event_id or new_event_id() for e in evs]
        body = "\n".join(e.with_id(i).to_json()
                         for e, i in zip(evs, ids)).encode("utf-8")
        self._w.call("POST", "/storage/events.jsonl",
                     _scope(app_id, channel_id), body=body)
        return ids

    def append_raw_lines(self, lines: Sequence[str], app_id: int,
                         channel_id: Optional[int] = None) -> None:
        """Pre-validated fast lane (same contract as the jsonlfs one):
        the bytes go to the server verbatim."""
        self._w.call("POST", "/storage/events.jsonl",
                     _scope(app_id, channel_id),
                     body="\n".join(lines).encode("utf-8"))

    # -- reads ------------------------------------------------------------
    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        quoted = urllib.parse.quote(event_id, safe="")
        status, payload = self._w.call(
            "GET", f"/storage/events/{quoted}.json",
            _scope(app_id, channel_id), ok=(200, 404))
        if status == 404:
            return None
        return Event.from_dict(payload)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        quoted = urllib.parse.quote(event_id, safe="")
        _, payload = self._w.call(
            "DELETE", f"/storage/events/{quoted}.json",
            _scope(app_id, channel_id))
        return bool(payload.get("found"))

    def delete_until(self, app_id, until_time,
                     channel_id: Optional[int] = None) -> int:
        p = _scope(app_id, channel_id)
        p["untilTime"] = until_time.isoformat()
        _, payload = self._w.call("POST", "/storage/delete_until.json", p)
        return int(payload.get("removed", 0))

    def aggregate_properties(self, app_id, entity_type, channel_id=None,
                             start_time=None, until_time=None,
                             required=None):
        """Server-side aggregation over the storage wire: the server
        answers from ITS backend's materialized state (one small JSON
        of current entities crosses the network, not the event
        history). A pre-aggregate-route server 404s — fall back to the
        client-side replay fold over ``find``."""
        from predictionio_tpu.data.event import _parse_time

        p = _scope(app_id, channel_id)
        p["entityType"] = entity_type
        if start_time is not None:
            p["startTime"] = start_time.isoformat()
        if until_time is not None:
            p["untilTime"] = until_time.isoformat()
        status, payload = self._w.call(
            "GET", "/storage/aggregate.json", p, ok=(200, 404))
        if status == 404:
            # super() does the hit/replay accounting for this path
            return super().aggregate_properties(
                app_id, entity_type, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
                required=required)
        from predictionio_tpu.utils import metrics

        if start_time is not None or until_time is not None:
            # bounded reads ALWAYS replay server-side (base contract)
            metrics.AGGREGATE_REPLAYS.inc(backend=self.metrics_backend,
                                          reason="bounded")
        # unbounded 200s are NOT counted as hits here: the server may
        # have served them via its own replay fallback, and it is the
        # server's base.aggregate_properties that counts hit vs replay
        # truthfully under ITS backend label
        out = {}
        for eid, rec in payload.items():
            out[eid] = PropertyMap(
                rec.get("properties") or {},
                first_updated=_parse_time(rec.get("firstUpdatedT")),
                last_updated=_parse_time(rec.get("lastUpdatedT")))
        return base._apply_required(out, required)

    def find(self, app_id, channel_id=None, start_time=None,
             until_time=None, entity_type=None, entity_id=None,
             event_names=None, target_entity_type=UNSET,
             target_entity_id=UNSET, limit=None,
             reversed=False) -> Iterable[Event]:
        p = _scope(app_id, channel_id)
        if start_time is not None:
            p["startTime"] = start_time.isoformat()
        if until_time is not None:
            p["untilTime"] = until_time.isoformat()
        if entity_type is not None:
            p["entityType"] = entity_type
        if entity_id is not None:
            p["entityId"] = entity_id
        if event_names is not None:
            p["event"] = list(event_names)
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                p["targetEntityTypeNull"] = "true"
            else:
                p["targetEntityType"] = target_entity_type
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                p["targetEntityIdNull"] = "true"
            else:
                p["targetEntityId"] = target_entity_id
        if limit is not None and limit >= 0:
            p["limit"] = int(limit)
        if reversed:
            p["reversed"] = "true"
        # tag the request as filtered even when every filter is a
        # default: `find` promises time ordering, which the raw
        # partition lane does not (storage order)
        p["limit"] = p.get("limit", -1)
        # split on BYTES, decode complete lines: a multibyte character
        # straddling a network-chunk boundary must not be corrupted
        tail = b""
        for chunk in self._w.stream(p):
            buf = tail + chunk
            lines = buf.split(b"\n")
            tail = lines.pop()
            for ln in lines:
                if ln.strip():
                    yield Event.from_json(ln.decode("utf-8"))
        if tail.strip():
            yield Event.from_json(tail.decode("utf-8"))


class RestPEvents(base.LEventsBackedPEvents):
    """Bulk reads: raw byte stream decoded client-side (native codec)."""

    def __init__(self, config: Optional[dict] = None):
        super().__init__(RestLEvents(config))
        self._w: _Wire = self._l._w

    def find_columnar_blocks(self, app_id, channel_id=None, start_time=None,
                             until_time=None, entity_type=None,
                             event_names=None, target_entity_type=UNSET,
                             value_property=None, default_value=1.0,
                             strict=True, block_size=1_000_000,
                             prefetch=0):
        """Fetch the UNFILTERED raw stream (for a jsonlfs-backed server:
        partition bytes, no server-side parsing) in ~8MB bites split at
        line boundaries, decode each with the native codec, and apply
        the filters over dictionary codes — the remote analog of the
        jsonlfs partition scan. ``prefetch`` is accepted but ignored:
        the wire stream is already pipelined by TCP readahead and
        decode happens per bite on this side."""
        del prefetch
        from predictionio_tpu.data.storage.jsonlfs import decode_jsonl_events

        BITE = 8 << 20
        buf = bytearray()

        def decode(data: bytes):
            for block in decode_jsonl_events(
                    data, start_time=start_time, until_time=until_time,
                    entity_type=entity_type, event_names=event_names,
                    target_entity_type=target_entity_type,
                    value_property=value_property,
                    default_value=default_value, strict=strict,
                    source=f"{self._w.url}/storage/events.jsonl"):
                for i in range(0, len(block), block_size):
                    yield block.take(slice(i, i + block_size))

        for chunk in self._w.stream(_scope(app_id, channel_id)):
            buf.extend(chunk)
            if len(buf) >= BITE:
                cut = buf.rfind(b"\n")
                if cut < 0:
                    continue
                data, buf = bytes(buf[:cut + 1]), bytearray(buf[cut + 1:])
                yield from decode(data)
        if buf:
            if not buf.endswith(b"\n"):
                buf.extend(b"\n")
            yield from decode(bytes(buf))

    def find_columnar(self, app_id, channel_id=None, start_time=None,
                      until_time=None, entity_type=None, event_names=None,
                      target_entity_type=UNSET, value_property=None,
                      default_value=1.0, strict=True):
        """Full scan = concatenated blocks, stably sorted by event time
        (the non-streaming contract other backends honor)."""
        import numpy as np

        from predictionio_tpu.data.columnar import ColumnarEvents

        blocks = list(self.find_columnar_blocks(
            app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names, target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict))
        batch = ColumnarEvents.concat(blocks)
        order = np.argsort(batch.event_times, kind="stable")
        return batch.take(order)
