"""REST-client event storage backend (``resthttp``).

The networked storage lane: LEvents/PEvents DAOs that speak HTTP to a
running event server's ``/storage/*`` wire, so an engine trains against
an event store living on ANOTHER machine/process — the defining
property of the reference's storage layer, where ``Storage.scala:360-391``
hands out DAOs for remote HBase/ES/JDBC services and training scans
regions over the network (``HBPEvents.scala:83-89``,
``JDBCPEvents.scala:31-100``). No DB services exist in this environment;
the event server IS the service, and the wire format is the same
event-JSONL every other component speaks.

- Typed CRUD/find ride ``/storage/events.json[l]`` (server-side
  filtering for ``find``).
- Bulk training reads (``find_columnar_blocks``) fetch the UNFILTERED
  raw stream — for a jsonlfs-backed server that is partition bytes with
  zero server-side parsing — and decode client-side with the native C++
  codec (``jsonlfs.decode_jsonl_events``), filters applied over
  dictionary codes. The network ships bytes; the training host pays the
  decode, exactly like a remote HBase scan.

Config (``PIO_STORAGE_SOURCES_<NAME>_{URL,SERVICE_KEY,TIMEOUT,
CA_FILE,INSECURE_SKIP_VERIFY}``): ``url`` e.g.
``http(s)://eventhost:7070``; ``service_key`` must match the server's
``--service-key``; for ``https`` URLs ``ca_file`` pins the server's
(typically self-signed) certificate; ``verify_hostname=false`` for
IP-only deployments with CN-only certs. Only the event DAOs exist — configure this
source for EVENTDATA and keep METADATA/MODELDATA local (the registry
raises per-kind capability errors otherwise).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import urllib.parse
import urllib.request
from typing import Iterable, List, Optional, Sequence

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import (
    Event,
    new_event_id,
    validate_event,
)
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import UNSET, StorageError
from predictionio_tpu.utils import faults, metrics, resilience
from predictionio_tpu.utils.tracing import outbound_context_headers, span

logger = logging.getLogger("pio.storage.resthttp")


class StorageUnavailable(StorageError):
    """The event server could not be reached. When the failure happened
    at CONNECT time the request provably never executed (retry class
    SAFE — any op, idempotent or not, may retry); after the request was
    sent the class is AMBIGUOUS."""

    def __init__(self, msg: str, retry_class: str = resilience.SAFE):
        super().__init__(msg)
        self.pio_retry_class = retry_class


class StorageTimeout(StorageError, TimeoutError):
    """A wire read exceeded the read timeout (the op may have run)."""

    pio_retry_class = resilience.AMBIGUOUS


class StorageServerError(StorageError):
    """HTTP 5xx (or 429) from the event server; carries the parsed
    ``Retry-After`` so backoff honors the server's own pacing."""

    pio_retry_class = resilience.AMBIGUOUS

    def __init__(self, msg: str, status: int,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.status = int(status)
        if retry_after is not None:
            self.pio_retry_after = retry_after


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None  # HTTP-date form: not worth a date parse here


class _PooledConn:
    """A checked-out keep-alive connection. ``close()`` returns the
    socket to the wire's idle pool when the response was fully drained
    and the server did not ask to close — so every existing
    ``conn.close()`` call site (call/stream/redirect hops) participates
    in reuse without changing; anything else really closes."""

    __slots__ = ("_conn", "_resp", "_wire")

    def __init__(self, conn, resp, wire: "_Wire"):
        self._conn, self._resp, self._wire = conn, resp, wire

    def close(self) -> None:
        conn, self._conn = self._conn, None
        if conn is None:
            return
        resp = self._resp
        try:
            reusable = resp is not None and resp.isclosed() \
                and not resp.will_close
        except Exception:
            reusable = False
        if reusable:
            self._wire._checkin(conn)
        else:
            conn.close()


class _Wire:
    """Shared HTTP plumbing for the storage wire, resilience included.

    Connections are reused: after a fully-drained HTTP/1.1 response the
    socket goes back to a per-wire idle pool (bounded by config
    ``pool_max`` / ``$PIO_STORAGE_POOL_MAX``, default 8) and the next
    call skips the TCP/TLS dial — the fleet router multiplies wire
    calls by the shard count, so fan-out must not pay a fresh connect
    per shard per op. A stale keep-alive (server closed the idle
    socket) fails the reused send fast and falls through to ONE fresh
    dial; it never consumes a retry-policy attempt.

    Timeouts are SPLIT: ``connect_timeout`` (config ``connect_timeout``
    / ``$PIO_STORAGE_CONNECT_TIMEOUT``, default 3s — a dead host must
    fail in seconds, not a minute) bounds the TCP/TLS dial;
    ``read_timeout`` (config ``read_timeout`` / legacy ``timeout`` /
    ``$PIO_STORAGE_READ_TIMEOUT``, default 60s) bounds each blocking
    read of the open socket. Every call runs under the shared
    :class:`~predictionio_tpu.utils.resilience.RetryPolicy` behind this
    URL's circuit breaker: connect-phase failures retry anything, 5xx /
    timeouts retry idempotent calls (event inserts ARE idempotent —
    the client assigns event ids before the first attempt and flags
    retries with ``X-Idempotency-Retry`` so the server dedups), and
    ``Retry-After`` floors the backoff.

    For an ``https://`` URL, ``ca_file`` pins the server certificate
    (the usual self-signed deployment); ``insecure_skip_verify`` (bool)
    disables verification entirely — test rigs only."""

    def __init__(self, config: Optional[dict] = None):
        cfg = config or {}
        self.url = (cfg.get("url") or "http://127.0.0.1:7070").rstrip("/")
        parts = urllib.parse.urlsplit(self.url)
        self._scheme = parts.scheme or "http"
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or (443 if self._scheme == "https" else 80)
        # an event server behind a reverse-proxy path prefix
        # (http://gw/pio-events) keeps its prefix on every wire path
        self._base_path = parts.path.rstrip("/")
        self.service_key = cfg.get("service_key") or ""
        legacy = cfg.get("timeout")
        self.connect_timeout = float(
            cfg.get("connect_timeout")
            or os.environ.get("PIO_STORAGE_CONNECT_TIMEOUT") or 3.0)
        self.read_timeout = float(
            cfg.get("read_timeout") or legacy
            or os.environ.get("PIO_STORAGE_READ_TIMEOUT") or 60.0)
        # the default op budget must survive one full read stall plus a
        # retry, or timeout-class failures can never actually retry
        # (PIO_STORAGE_OP_DEADLINE, when set, overrides this)
        self.policy = resilience.RetryPolicy.from_env(
            default_deadline=max(30.0, 2.0 * self.read_timeout
                                 + 2.0 * self.connect_timeout))
        self.breaker = resilience.breaker_for(self.url)
        self._pool: list = []
        self._pool_lock = threading.Lock()
        self._pool_max = int(
            cfg.get("pool_max")
            or os.environ.get("PIO_STORAGE_POOL_MAX") or 8)
        self.pool_reuses = 0  # kept-alive sends (observability/tests)
        self._ssl_ctx = None
        if self._scheme == "https":
            import ssl

            ca = cfg.get("ca_file")
            skip = str(cfg.get("insecure_skip_verify", "")
                       ).strip().lower() in ("1", "true", "yes")
            ctx = ssl.create_default_context(cafile=ca or None)
            # hostname verification stays ON by default even with a
            # pinned ca_file (a CA bundle signs many hosts); IP-only
            # deployments with CN-only self-signed certs opt out via
            # verify_hostname=false
            if str(cfg.get("verify_hostname", "")
                   ).strip().lower() in ("0", "false", "no"):
                ctx.check_hostname = False
            if skip:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl_ctx = ctx
        # the wire dials the event server DIRECTLY: it is an internal
        # service hop, and routing storage traffic through an ambient
        # egress proxy (which the pre-split-timeout urllib lane did by
        # accident) is the classic way internal traffic breaks. Say so
        # loudly instead of failing with an opaque connect error.
        proxies = urllib.request.getproxies()
        if proxies.get(self._scheme) and \
                not urllib.request.proxy_bypass(self._host):
            logger.warning(
                "%s_proxy is set but the storage wire connects to %s "
                "directly (proxies are not supported on this hop); "
                "add the host to no_proxy to silence this",
                self._scheme, self.url)

    def _full(self, path: str, params: dict) -> str:
        """Path + query (http.client takes the host separately)."""
        q = {"serviceKey": self.service_key}
        for k, v in params.items():
            if v is not None:
                q[k] = v
        return f"{self._base_path}{path}?" + \
            urllib.parse.urlencode(q, doseq=True)

    def _headers(self, body: Optional[bytes], attempt: int,
                 replay_possible: bool = False) -> dict:
        """Observability context on EVERY wire call (request id +
        traceparent, so the server's spans join the caller's trace).
        ``X-Idempotency-Retry`` goes out only when a PRIOR attempt of
        this op failed AMBIGUOUSLY — i.e. the server may have committed
        it. A SAFE failure (connect refused: the request provably never
        left) must NOT flag the retry: the server's byte-digest replay
        cache would otherwise swallow a legitimate id-less append whose
        bytes happen to match an earlier committed one."""
        headers = dict(outbound_context_headers())
        if body is not None:
            headers["Content-Type"] = "application/x-jsonlines"
        if attempt > 0 and replay_possible:
            headers["X-Idempotency-Retry"] = str(attempt)
        return headers

    def _checkout(self):
        with self._pool_lock:
            return self._pool.pop() if self._pool else None

    def _checkin(self, conn) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_max:
                self._pool.append(conn)
                return
        conn.close()

    def close(self) -> None:
        """Drain the idle keep-alive pool (checked-out connections
        close themselves through ``_PooledConn``)."""
        with self._pool_lock:
            idle, self._pool = self._pool, []
        for conn in idle:
            conn.close()

    def _dial(self):
        """TCP/TLS connect under the connect deadline. Dial failures
        are SAFE — the request provably never left."""
        import http.client

        try:
            if self._scheme == "https":
                conn = http.client.HTTPSConnection(
                    self._host, self._port, timeout=self.connect_timeout,
                    context=self._ssl_ctx)
            else:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self.connect_timeout)
            conn.connect()
        except (TimeoutError, socket.timeout) as e:
            raise StorageUnavailable(
                f"event server unreachable at {self.url}: connect timed "
                f"out after {self.connect_timeout}s",
                retry_class=resilience.SAFE) from e
        except OSError as e:
            # refused / DNS / TLS dial failure: the request never left
            raise StorageUnavailable(
                f"event server unreachable at {self.url}: {e}",
                retry_class=resilience.SAFE) from e
        # the dial is done: from here each blocking socket op runs
        # under the (longer) read deadline
        conn.sock.settimeout(self.read_timeout)
        # small request/response segments must not wait out a delayed
        # ACK (Nagle costs a flat ~40ms per exchange on keep-alive)
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        except OSError:
            pass  # non-TCP transports (unix sockets in tests)
        return conn

    def _request_once(self, method: str, pathq: str,
                      body: Optional[bytes], headers: dict):
        """One HTTP exchange under the split timeouts. Returns
        ``(conn, resp)`` — the caller reads and closes (the conn is a
        :class:`_PooledConn`, so a clean close rejoins the keep-alive
        pool). Dial failures are SAFE (nothing was sent); post-send
        failures are AMBIGUOUS — except a failure in the SEND phase on
        a REUSED idle connection, the classic stale keep-alive (the
        server is allowed to close an idle socket at any time): that
        conn is discarded and the exchange falls through to one fresh
        dial. Once the send completed on a reused socket, a dropped
        response is AMBIGUOUS exactly like the fresh-dial path — the
        server may have executed, and a silent redial would re-send
        behind the back of the ``idempotent=False`` retry protection
        (an unkeyed event batch appended twice, a committed delete
        replayed)."""
        import http.client

        pooled = self._checkout()
        if pooled is not None:
            sent = False
            try:
                pooled.request(method, pathq, body=body, headers=headers)
                sent = True
                resp = pooled.getresponse()
                self.pool_reuses += 1
                return _PooledConn(pooled, resp, self), resp
            except (TimeoutError, socket.timeout) as e:
                # time passed and the server may have executed: this is
                # a real timeout, not a stale socket — no silent redial
                pooled.close()
                raise StorageTimeout(
                    f"{method} {self.url}: no response within "
                    f"{self.read_timeout}s") from e
            except (OSError, http.client.HTTPException) as e:
                pooled.close()
                if sent:
                    raise StorageUnavailable(
                        f"event server dropped the connection at "
                        f"{self.url}: {e}",
                        retry_class=resilience.AMBIGUOUS) from e
                # stale keep-alive at send: fall through, redial
        conn = self._dial()
        try:
            conn.request(method, pathq, body=body, headers=headers)
            resp = conn.getresponse()
            return _PooledConn(conn, resp, self), resp
        except (TimeoutError, socket.timeout) as e:
            conn.close()
            raise StorageTimeout(
                f"{method} {self.url}: no response within "
                f"{self.read_timeout}s") from e
        except (OSError, http.client.HTTPException) as e:
            # BadStatusLine & co are HTTPException, NOT OSError — a
            # server killed mid-response must still classify AMBIGUOUS
            # (it may have committed) and must not leak the connection
            conn.close()
            raise StorageUnavailable(
                f"event server dropped the connection at {self.url}: {e}",
                retry_class=resilience.AMBIGUOUS) from e

    _MAX_REDIRECTS = 3

    def _request_redirects(self, method: str, pathq: str,
                           body: Optional[bytes], headers: dict):
        """``_request_once`` plus bounded SAME-ORIGIN redirect following
        for GETs — the old urllib lane followed read redirects (e.g. a
        gateway's trailing-slash canonicalization) and the http.client
        rewrite must not regress that. A cross-origin ``Location``
        (scheme/host/port change, e.g. an http->https upgrade) is a
        config error surfaced loudly: silently re-dialing a different
        origin would hide the misconfigured storage URL. Writes are
        never redirected (urllib's POST handling re-issued as GET —
        never correct on this wire)."""
        for _ in range(self._MAX_REDIRECTS):
            conn, resp = self._request_once(method, pathq, body, headers)
            if method != "GET" or resp.status not in (301, 302, 303,
                                                      307, 308):
                return conn, resp
            loc = resp.headers.get("Location")
            try:
                resp.read()
            finally:
                conn.close()
            if not loc:
                raise StorageError(
                    f"{method} {pathq}: {resp.status} redirect with no "
                    "Location header")
            parts = urllib.parse.urlsplit(loc)
            if parts.scheme or parts.netloc:
                port = parts.port or (
                    443 if (parts.scheme or self._scheme) == "https"
                    else 80)
                if (parts.scheme or self._scheme) != self._scheme or \
                        parts.hostname != self._host or \
                        port != self._port:
                    raise StorageError(
                        f"{method} {pathq}: redirected off-origin to "
                        f"{loc}; update the storage URL ({self.url}) to "
                        "the canonical endpoint")
                pathq = parts.path + (f"?{parts.query}"
                                      if parts.query else "")
            else:
                pathq = loc
        raise StorageError(
            f"{method}: more than {self._MAX_REDIRECTS} redirects from "
            f"{self.url}")

    def _check_status(self, status: int, raw: bytes, context: str,
                      retry_after_hdr: Optional[str], ok) -> None:
        """ONE definition of which wire statuses are retryable: 5xx and
        429 raise StorageServerError (Retry-After parsed), other
        not-ok statuses are permanent StorageErrors."""
        if status in ok:
            return
        try:
            payload = json.loads(raw.decode("utf-8"))
            msg = payload.get("message", payload)
        except Exception:
            msg = raw.decode("utf-8", "replace")
        if status >= 500 or status == 429:
            raise StorageServerError(
                f"{context} -> {status}: {msg}", status,
                _parse_retry_after(retry_after_hdr))
        raise StorageError(f"{context} -> {status}: {msg}")

    def _run_resilient(self, attempt_fn, op: str,
                       idempotent=True, retry_state: Optional[dict] = None):
        """Breaker + retry shell shared by ``call`` and ``stream``.
        ``retry_state`` (when given) gets ``ambiguous=True`` once any
        failed attempt may have executed server-side — the attempt fn
        reads it to decide whether the next request flags itself as a
        possible replay."""
        if not resilience.enabled():
            return attempt_fn(0)

        def on_retry(attempt: int, exc: BaseException,
                     delay: float) -> None:
            metrics.STORAGE_RETRIES.inc(backend="resthttp", op=op)
            if retry_state is not None and \
                    resilience.classify(exc) == resilience.AMBIGUOUS:
                retry_state["ambiguous"] = True

        return base.run_guarded(self.breaker, self.policy, attempt_fn,
                                idempotent=idempotent, on_retry=on_retry)

    def call(self, method: str, path: str, params: dict,
             body: Optional[bytes] = None, ok=(200,),
             op: Optional[str] = None, idempotent=True):
        """One JSON wire call with retries. ``op`` names the logical
        DAO op for fault-injection matching and retry metrics. Wire
        calls default idempotent (reads, idempotent admin verbs, and
        id-carrying event appends the server dedups); a caller sending
        id-LESS event lines must pass ``idempotent=False`` — the
        server cannot dedup what carries no key."""
        opname = op or f"{method} {path}"
        pathq = self._full(path, params)
        retry_state = {"ambiguous": False}
        with span(f"resthttp {method} {path}",
                  attributes={"url": self.url}):
            def attempt(n: int):
                # injected faults sit INSIDE the retry loop, like real
                # ones; a torn directive means "the server committed
                # but the response was lost" — execute fully, discard
                # the response, fail ambiguously (the retry + the
                # server-side dedup then prove exactly-once)
                import http.client

                torn = faults.maybe_fault("resthttp", opname)
                conn, resp = self._request_redirects(
                    method, pathq, body,
                    self._headers(body, n,
                                  replay_possible=retry_state["ambiguous"]))
                try:
                    raw = resp.read()
                    status = resp.status
                    retry_after = resp.headers.get("Retry-After")
                except (TimeoutError, socket.timeout) as e:
                    raise StorageTimeout(
                        f"{method} {path}: response stalled past "
                        f"{self.read_timeout}s") from e
                except (OSError, http.client.HTTPException) as e:
                    # IncompleteRead = killed mid-response: AMBIGUOUS
                    raise StorageUnavailable(
                        f"{method} {path}: response truncated by "
                        f"{self.url}: {e}",
                        retry_class=resilience.AMBIGUOUS) from e
                finally:
                    conn.close()
                if torn is not None:
                    raise torn.error()
                self._check_status(status, raw, f"{method} {path}",
                                   retry_after, ok)
                try:
                    payload = json.loads(raw.decode("utf-8"))
                except Exception:
                    payload = {"message": raw.decode("utf-8", "replace")}
                return status, payload

            return self._run_resilient(attempt, opname,
                                       idempotent=idempotent,
                                       retry_state=retry_state)

    def stream(self, params: dict, op: str = "find"):
        """GET /storage/events.jsonl as a raw byte-chunk iterator. The
        wire span (and the retry loop) covers the connect + response
        headers; once bytes flow, a failure is NOT replayable here —
        the consumer has already seen a prefix — and surfaces as a
        StorageError."""
        pathq = self._full("/storage/events.jsonl", params)
        with span("resthttp GET /storage/events.jsonl",
                  attributes={"url": self.url, "streaming": True}):
            def attempt(n: int):
                torn = faults.maybe_fault("resthttp", op)
                conn, resp = self._request_redirects(
                    "GET", pathq, None, self._headers(None, n))
                if torn is not None:
                    # response lost after the server answered: the
                    # directive must MANIFEST (a silently-dropped torn
                    # rule would burn its budget testing nothing)
                    conn.close()
                    raise torn.error()
                if resp.status != 200:
                    try:
                        raw = resp.read()
                    finally:
                        conn.close()
                    self._check_status(
                        resp.status, raw, "GET /storage/events.jsonl",
                        resp.headers.get("Retry-After"), ok=(200,))
                return conn, resp

            conn, resp = self._run_resilient(attempt, op)

        def chunks():
            import http.client

            try:
                while True:
                    c = resp.read(1 << 22)
                    if not c:
                        break
                    yield c
            except (TimeoutError, socket.timeout) as e:
                raise StorageTimeout(
                    f"storage stream from {self.url} stalled past "
                    f"{self.read_timeout}s") from e
            except (OSError, http.client.HTTPException) as e:
                # truncated chunked framing (server died mid-stream)
                raise StorageError(
                    f"storage stream from {self.url} interrupted: "
                    f"{e}") from e
            finally:
                conn.close()
        return chunks()


def _scope(app_id: int, channel_id: Optional[int]) -> dict:
    p = {"appId": int(app_id)}
    if channel_id is not None:
        p["channelId"] = int(channel_id)
    return p


class RestLEvents(base.LEvents):
    """LEvents client over the event server's storage wire.

    Resilience lives IN the wire (retries + this URL's breaker around
    every call), so the registry's DAO wrapper must not stack a second
    retry loop on top — ``self_resilient`` tells it so. Event writes
    are idempotent: ids are client-generated before the first attempt
    and the server dedups retried appends (``X-Idempotency-Retry``)."""

    metrics_backend = "resthttp"
    self_resilient = True
    idempotent_event_writes = True

    def __init__(self, config: Optional[dict] = None):
        self._w = _Wire(config)
        # per-endpoint availability domain: the wire URL
        self.resilience_endpoint = self._w.url

    # -- lifecycle --------------------------------------------------------
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        _, p = self._w.call("POST", "/storage/init.json",
                            _scope(app_id, channel_id), op="init")
        return bool(p.get("ok"))

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        _, p = self._w.call("POST", "/storage/remove.json",
                            _scope(app_id, channel_id), op="remove")
        return bool(p.get("ok"))

    def close(self) -> None:
        self._w.close()  # drain the keep-alive pool

    def shutdown(self) -> None:
        self._w.close()

    # -- writes -----------------------------------------------------------
    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Iterable[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        evs = list(events)
        for e in evs:
            validate_event(e)
        # ids assigned ONCE, before the wire's retry loop: a retried
        # POST replays the same ids, which the server dedups
        ids = [e.event_id or new_event_id() for e in evs]
        body = "\n".join(e.with_id(i).to_json()
                         for e, i in zip(evs, ids)).encode("utf-8")
        self._w.call("POST", "/storage/events.jsonl",
                     _scope(app_id, channel_id), body=body,
                     op="insert_batch")
        return ids

    def append_raw_lines(self, lines: Sequence[str], app_id: int,
                         channel_id: Optional[int] = None) -> None:
        """Pre-validated fast lane (same contract as the jsonlfs one):
        the bytes go to the server verbatim. Ambiguous failures retry
        only when every line carries a TOP-LEVEL ``eventId`` (the
        idempotency key the server-side dedup needs — a nested
        properties key must not fool the check); id-less lines still
        retry provably-unsent failures (connection refused). The exact
        per-line parse is LAZY: only a retry decision pays it, never
        the bulk-ingest success path."""
        lines = list(lines)

        def keyed() -> bool:
            for ln in lines:
                try:
                    d = json.loads(ln)
                except ValueError:
                    return False
                if not isinstance(d, dict) or not d.get("eventId"):
                    return False
            return True

        self._w.call("POST", "/storage/events.jsonl",
                     _scope(app_id, channel_id),
                     body="\n".join(lines).encode("utf-8"),
                     op="append_raw_lines", idempotent=keyed)

    # -- reads ------------------------------------------------------------
    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        quoted = urllib.parse.quote(event_id, safe="")
        status, payload = self._w.call(
            "GET", f"/storage/events/{quoted}.json",
            _scope(app_id, channel_id), ok=(200, 404), op="get")
        if status == 404:
            return None
        return Event.from_dict(payload)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        # idempotent=False: the STATE change replays fine, but the
        # RESPONSE doesn't — a retry after a committed first attempt
        # returns found=false for an event that was just deleted.
        # Ambiguous failures surface to the caller; provably-unsent
        # ones (connect refused) still retry.
        quoted = urllib.parse.quote(event_id, safe="")
        _, payload = self._w.call(
            "DELETE", f"/storage/events/{quoted}.json",
            _scope(app_id, channel_id), op="delete", idempotent=False)
        return bool(payload.get("found"))

    def delete_until(self, app_id, until_time,
                     channel_id: Optional[int] = None) -> int:
        # idempotent=False for the same reason as delete(): a replayed
        # attempt reports removed=0 after the first removed N.
        p = _scope(app_id, channel_id)
        p["untilTime"] = until_time.isoformat()
        _, payload = self._w.call("POST", "/storage/delete_until.json", p,
                                  op="delete_until", idempotent=False)
        return int(payload.get("removed", 0))

    # -- tail reads (find_since contract, base.py) -------------------------
    # The remote server's backend mints the cursor; it crosses the wire
    # as opaque JSON both ways, so a resthttp consumer tails whatever
    # store the event server actually runs (memory/sqlite/jsonlfs, or
    # another resthttp hop).

    def find_since(self, app_id, channel_id=None, cursor=None, limit=None):
        p = _scope(app_id, channel_id)
        if cursor is not None:
            # the cursor rides in the request BODY: a jsonlfs watermark
            # carries one entry per partition, and a big store's cursor
            # in the query string would overflow the server's
            # request-line cap (64 KB) — permanently wedging the tail
            body = {"cursor": cursor}
            if limit is not None:
                body["limit"] = int(limit)
            _, payload = self._w.call(
                "POST", "/storage/tail.json", p,
                body=json.dumps(body).encode("utf-8"), op="find_since")
        else:
            if limit is not None:
                p["limit"] = int(limit)
            _, payload = self._w.call("GET", "/storage/tail.json", p,
                                      op="find_since")
        events = [Event.from_dict(d) for d in payload.get("events", [])]
        return events, payload.get("cursor") or {}

    def tail_cursor(self, app_id, channel_id=None):
        p = _scope(app_id, channel_id)
        p["position"] = "end"
        _, payload = self._w.call("GET", "/storage/tail.json", p,
                                  op="tail_cursor")
        return payload.get("cursor") or {}

    def tail_watermark(self, app_id, channel_id=None):
        p = _scope(app_id, channel_id)
        p["watermark"] = "true"
        _, payload = self._w.call("GET", "/storage/tail.json", p,
                                  op="tail_watermark")
        return payload.get("watermark")

    def aggregate_properties(self, app_id, entity_type, channel_id=None,
                             start_time=None, until_time=None,
                             required=None):
        """Server-side aggregation over the storage wire: the server
        answers from ITS backend's materialized state (one small JSON
        of current entities crosses the network, not the event
        history). A pre-aggregate-route server 404s — fall back to the
        client-side replay fold over ``find``."""
        from predictionio_tpu.data.event import _parse_time

        p = _scope(app_id, channel_id)
        p["entityType"] = entity_type
        if start_time is not None:
            p["startTime"] = start_time.isoformat()
        if until_time is not None:
            p["untilTime"] = until_time.isoformat()
        status, payload = self._w.call(
            "GET", "/storage/aggregate.json", p, ok=(200, 404),
            op="aggregate")
        if status == 404:
            # super() does the hit/replay accounting for this path
            return super().aggregate_properties(
                app_id, entity_type, channel_id=channel_id,
                start_time=start_time, until_time=until_time,
                required=required)
        from predictionio_tpu.utils import metrics

        if start_time is not None or until_time is not None:
            # bounded reads ALWAYS replay server-side (base contract)
            metrics.AGGREGATE_REPLAYS.inc(backend=self.metrics_backend,
                                          reason="bounded")
        # unbounded 200s are NOT counted as hits here: the server may
        # have served them via its own replay fallback, and it is the
        # server's base.aggregate_properties that counts hit vs replay
        # truthfully under ITS backend label
        out = {}
        for eid, rec in payload.items():
            out[eid] = PropertyMap(
                rec.get("properties") or {},
                first_updated=_parse_time(rec.get("firstUpdatedT")),
                last_updated=_parse_time(rec.get("lastUpdatedT")))
        return base._apply_required(out, required)

    def find(self, app_id, channel_id=None, start_time=None,
             until_time=None, entity_type=None, entity_id=None,
             event_names=None, target_entity_type=UNSET,
             target_entity_id=UNSET, limit=None,
             reversed=False) -> Iterable[Event]:
        p = _scope(app_id, channel_id)
        if start_time is not None:
            p["startTime"] = start_time.isoformat()
        if until_time is not None:
            p["untilTime"] = until_time.isoformat()
        if entity_type is not None:
            p["entityType"] = entity_type
        if entity_id is not None:
            p["entityId"] = entity_id
        if event_names is not None:
            p["event"] = list(event_names)
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                p["targetEntityTypeNull"] = "true"
            else:
                p["targetEntityType"] = target_entity_type
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                p["targetEntityIdNull"] = "true"
            else:
                p["targetEntityId"] = target_entity_id
        if limit is not None and limit >= 0:
            p["limit"] = int(limit)
        if reversed:
            p["reversed"] = "true"
        # tag the request as filtered even when every filter is a
        # default: `find` promises time ordering, which the raw
        # partition lane does not (storage order)
        p["limit"] = p.get("limit", -1)
        # split on BYTES, decode complete lines: a multibyte character
        # straddling a network-chunk boundary must not be corrupted
        tail = b""
        for chunk in self._w.stream(p, op="find"):
            buf = tail + chunk
            lines = buf.split(b"\n")
            tail = lines.pop()
            for ln in lines:
                if ln.strip():
                    yield Event.from_json(ln.decode("utf-8"))
        if tail.strip():
            yield Event.from_json(tail.decode("utf-8"))


class RestPEvents(base.LEventsBackedPEvents):
    """Bulk reads: raw byte stream decoded client-side (native codec)."""

    def __init__(self, config: Optional[dict] = None):
        super().__init__(RestLEvents(config))
        self._w: _Wire = self._l._w

    def find_columnar_blocks(self, app_id, channel_id=None, start_time=None,
                             until_time=None, entity_type=None,
                             event_names=None, target_entity_type=UNSET,
                             value_property=None, default_value=1.0,
                             strict=True, block_size=1_000_000,
                             prefetch=0):
        """Fetch the UNFILTERED raw stream (for a jsonlfs-backed server:
        partition bytes, no server-side parsing) in ~8MB bites split at
        line boundaries, decode each with the native codec, and apply
        the filters over dictionary codes — the remote analog of the
        jsonlfs partition scan. ``prefetch`` is accepted but ignored:
        the wire stream is already pipelined by TCP readahead and
        decode happens per bite on this side."""
        del prefetch
        from predictionio_tpu.data.storage.jsonlfs import decode_jsonl_events

        BITE = 8 << 20
        buf = bytearray()

        def decode(data: bytes):
            for block in decode_jsonl_events(
                    data, start_time=start_time, until_time=until_time,
                    entity_type=entity_type, event_names=event_names,
                    target_entity_type=target_entity_type,
                    value_property=value_property,
                    default_value=default_value, strict=strict,
                    source=f"{self._w.url}/storage/events.jsonl"):
                for i in range(0, len(block), block_size):
                    yield block.take(slice(i, i + block_size))

        for chunk in self._w.stream(_scope(app_id, channel_id),
                                    op="find_columnar_blocks"):
            buf.extend(chunk)
            if len(buf) >= BITE:
                cut = buf.rfind(b"\n")
                if cut < 0:
                    continue
                data, buf = bytes(buf[:cut + 1]), bytearray(buf[cut + 1:])
                yield from decode(data)
        if buf:
            if not buf.endswith(b"\n"):
                buf.extend(b"\n")
            yield from decode(bytes(buf))

    def find_columnar(self, app_id, channel_id=None, start_time=None,
                      until_time=None, entity_type=None, event_names=None,
                      target_entity_type=UNSET, value_property=None,
                      default_value=1.0, strict=True):
        """Full scan = concatenated blocks, stably sorted by event time
        (the non-streaming contract other backends honor)."""
        import numpy as np

        from predictionio_tpu.data.columnar import ColumnarEvents

        blocks = list(self.find_columnar_blocks(
            app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names, target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict))
        batch = ColumnarEvents.concat(blocks)
        order = np.argsort(batch.event_times, kind="stable")
        return batch.take(order)
