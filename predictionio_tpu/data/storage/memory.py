"""In-memory storage backend (test + dev; cf. reference test-mode clients).

Provides every DAO over plain dicts with the exact filter semantics of the
reference's HBase scan construction (``HBEventsUtil.scala:286-410``): time
range is [start, until), equality filters on entity/event/target fields,
``target_entity_type=None`` (explicitly) matches only events WITHOUT a
target entity.
"""

from __future__ import annotations

import datetime as _dt
import itertools
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from predictionio_tpu.data.aggregator import (
    AGGREGATOR_EVENT_NAMES,
    EntityState,
    fold_event,
    fold_events,
    states_to_property_maps,
)
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event, new_event_id, validate_event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    UNSET, AccessKey, App, Channel, EngineInstance, EvaluationInstance, Model,
)


def match_event(
    e: Event,
    start_time=None,
    until_time=None,
    entity_type=None,
    entity_id=None,
    event_names=None,
    target_entity_type=UNSET,
    target_entity_id=UNSET,
) -> bool:
    """Shared filter predicate used by memory/sqlite post-filters."""
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in set(event_names):
        return False
    if target_entity_type is not UNSET and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not UNSET and e.target_entity_id != target_entity_id:
        return False
    return True


class MemLEvents(base.LEvents):
    metrics_backend = "memory"
    # insert is an upsert keyed by event id: a retried insert with the
    # same (pre-assigned) ids replays to the identical state
    idempotent_event_writes = True

    def __init__(self, config: Optional[dict] = None):
        # (app_id, channel_id) -> {event_id: Event}; insertion order kept
        self._tables: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}
        # write-through materialized aggregate: the same scope key ->
        # {(entity_type, entity_id): EntityState}, updated on every
        # special-event insert/delete — the unbounded
        # aggregate_properties reads it instead of replaying the table
        self._props: Dict[Tuple[int, Optional[int]],
                          Dict[Tuple[str, str], EntityState]] = {}
        # arrival-ordered event ids per scope — the tail-read (find_since)
        # order; an id-keyed upsert appends AGAIN so tail consumers see
        # the newest version (re-delivery, never a miss), and deleted ids
        # are skipped at read time (until compaction, below)
        self._seq: Dict[Tuple[int, Optional[int]], List[str]] = {}
        # tail generation per scope: bumped whenever positions in _seq
        # stop meaning what an outstanding cursor recorded (scope remove,
        # tombstone compaction) so the cursor resets to a full replay.
        # NEVER popped — it must survive a remove + re-ingest, where the
        # rebuilt _seq can grow past an old cursor's position
        self._gen: Dict[Tuple[int, Optional[int]], int] = {}
        self._lock = threading.RLock()

    def _key(self, app_id, channel_id):
        return (int(app_id), None if channel_id is None else int(channel_id))

    def init(self, app_id, channel_id=None) -> bool:
        with self._lock:
            self._tables.setdefault(self._key(app_id, channel_id), {})
        return True

    def remove(self, app_id, channel_id=None) -> bool:
        from predictionio_tpu.utils import metrics

        with self._lock:
            if self._props.pop(self._key(app_id, channel_id), None) \
                    is not None:
                metrics.AGGREGATE_SCOPE_DROPS.inc(
                    backend=self.metrics_backend)
            key = self._key(app_id, channel_id)
            if self._seq.pop(key, None) is not None:
                self._gen[key] = self._gen.get(key, 0) + 1
            return self._tables.pop(key, None) is not None

    def close(self) -> None:
        pass

    def _refold_entity_locked(self, key, entity_type: str,
                              entity_id: str) -> None:
        """Re-derive ONE entity's state from its (small) event history —
        the out-of-order / delete repair path. Caller holds the lock."""
        evs = [e for e in self._tables.get(key, {}).values()
               if e.entity_type == entity_type and e.entity_id == entity_id
               and e.event in AGGREGATOR_EVENT_NAMES]
        props = self._props.setdefault(key, {})
        st = fold_events(evs)
        if st is None:
            props.pop((entity_type, entity_id), None)
        else:
            props[(entity_type, entity_id)] = st

    def _fold_in_locked(self, key, event: Event) -> None:
        if event.event not in AGGREGATOR_EVENT_NAMES:
            return
        props = self._props.setdefault(key, {})
        pkey = (event.entity_type, event.entity_id)
        st = props.get(pkey)
        if st is not None and st.last_updated is not None \
                and event.event_time < st.last_updated:
            # out-of-order arrival: the replay would fold this BEFORE
            # already-applied events — re-derive from history instead
            self._refold_entity_locked(key, *pkey)
        else:
            props[pkey] = fold_event(st, event)

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        validate_event(event)
        eid = event.event_id or new_event_id()
        with self._lock:
            key = self._key(app_id, channel_id)
            table = self._tables.setdefault(key, {})
            replaced = table.get(eid)
            table[eid] = event.with_id(eid)
            self._seq.setdefault(key, []).append(eid)
            if replaced is not None:
                # upsert semantics: the replaced event's fold contribution
                # is gone — re-derive the touched entities. When NEITHER
                # side is special the fold state cannot have changed, so
                # the common idempotent-retry of a non-special event
                # stays O(1) instead of rescanning the scope.
                if replaced.event in AGGREGATOR_EVENT_NAMES:
                    self._refold_entity_locked(
                        key, replaced.entity_type, replaced.entity_id)
                if event.event in AGGREGATOR_EVENT_NAMES:
                    self._refold_entity_locked(
                        key, event.entity_type, event.entity_id)
                # each upsert leaves a duplicate _seq entry behind —
                # the same unbounded-growth hazard as delete tombstones
                self._compact_seq_locked(key)
            else:
                self._fold_in_locked(key, event)
        return eid

    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        with self._lock:
            return self._tables.get(self._key(app_id, channel_id), {}).get(event_id)

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        with self._lock:
            key = self._key(app_id, channel_id)
            table = self._tables.get(key, {})
            gone = table.pop(event_id, None)
            if gone is not None:
                if gone.event in AGGREGATOR_EVENT_NAMES:
                    self._refold_entity_locked(key, gone.entity_type,
                                               gone.entity_id)
                self._compact_seq_locked(key)
            return gone is not None

    def _compact_seq_locked(self, key) -> None:
        """Drop tombstones (deleted ids) and upsert duplicates from
        ``_seq`` once they outnumber the live events — without this, a
        long-lived store under retention trimming (``delete_until``
        walks ``delete``) grows one dead entry per ever-inserted event.
        Compaction renumbers positions, so the generation bumps and
        outstanding tail cursors replay. Caller holds the lock."""
        seq = self._seq.get(key)
        table = self._tables.get(key, {})
        if seq is None or len(seq) < 64 or len(seq) <= 2 * len(table):
            return
        kept_rev: List[str] = []
        seen = set()
        for eid in reversed(seq):
            if eid in table and eid not in seen:
                seen.add(eid)
                kept_rev.append(eid)
        kept_rev.reverse()
        self._seq[key] = kept_rev
        self._gen[key] = self._gen.get(key, 0) + 1

    def materialized_aggregate(self, app_id, entity_type, channel_id=None
                               ) -> Optional[Dict[str, PropertyMap]]:
        with self._lock:
            props = self._props.get(self._key(app_id, channel_id), {})
            states = {eid: st for (etype, eid), st in props.items()
                      if etype == entity_type}
        return states_to_property_maps(states)

    def find(self, app_id, channel_id=None, start_time=None, until_time=None,
             entity_type=None, entity_id=None, event_names=None,
             target_entity_type=UNSET, target_entity_id=UNSET,
             limit=None, reversed=False) -> Iterable[Event]:
        with self._lock:
            events = list(self._tables.get(self._key(app_id, channel_id), {}).values())
        out = [e for e in events if match_event(
            e, start_time, until_time, entity_type, entity_id, event_names,
            target_entity_type, target_entity_id)]
        out.sort(key=lambda e: e.event_time, reverse=bool(reversed))
        if limit is not None and limit >= 0:
            out = out[:limit]
        return iter(out)

    # -- tail reads (find_since contract, base.py) -------------------------

    def find_since(self, app_id, channel_id=None, cursor=None, limit=None):
        key = self._key(app_id, channel_id)
        pos = int(cursor.get("pos", 0)) if cursor else 0
        cgen = int(cursor.get("gen", 0)) if cursor else 0
        out: List[Event] = []
        with self._lock:
            seq = self._seq.get(key, [])
            table = self._tables.get(key, {})
            gen = self._gen.get(key, 0)
            if cgen != gen or pos > len(seq):
                # positions stopped meaning what the cursor recorded
                # (scope removed + re-ingested, or _seq compacted):
                # replay from the start (contract in base.py). The
                # position check alone cannot catch a re-ingest that
                # grew PAST the old cursor — the generation does.
                pos = 0
            while pos < len(seq):
                if limit is not None and len(out) >= int(limit):
                    break
                e = table.get(seq[pos])
                if e is not None:
                    out.append(e)
                pos += 1
        return out, {"kind": "memory", "pos": pos, "gen": gen}

    def tail_cursor(self, app_id, channel_id=None):
        key = self._key(app_id, channel_id)
        with self._lock:
            seq = self._seq.get(key, [])
            return {"kind": "memory", "pos": len(seq),
                    "gen": self._gen.get(key, 0)}

    def tail_watermark(self, app_id, channel_id=None):
        key = self._key(app_id, channel_id)
        with self._lock:
            seq = self._seq.get(key, [])
            table = self._tables.get(key, {})
            last = next((table[eid] for eid in reversed(seq)
                         if eid in table), None)
            cursor = {"kind": "memory", "pos": len(seq),
                      "gen": self._gen.get(key, 0)}
        return {
            "cursor": cursor,
            "lastEventId": None if last is None else last.event_id,
            "lastEventTime": None if last is None
            else last.event_time.isoformat(),
        }


class _IdTable:
    """Auto-increment record table keyed by int id."""

    def __init__(self):
        self.rows: Dict[int, Any] = {}
        self.next_id = itertools.count(1)
        self.lock = threading.RLock()


class MemApps(base.Apps):
    def __init__(self, config: Optional[dict] = None):
        self._t = _IdTable()

    def insert(self, app: App) -> Optional[int]:
        with self._t.lock:
            if any(a.name == app.name for a in self._t.rows.values()):
                return None
            if app.id:
                if app.id in self._t.rows:
                    return None  # explicit id conflict (matches sqlite)
                aid = app.id
            else:
                aid = next(self._t.next_id)
                while aid in self._t.rows:
                    aid = next(self._t.next_id)
            self._t.rows[aid] = App(aid, app.name, app.description)
            return aid

    def get(self, app_id):
        return self._t.rows.get(int(app_id))

    def get_by_name(self, name):
        return next((a for a in self._t.rows.values() if a.name == name), None)

    def get_all(self):
        return sorted(self._t.rows.values(), key=lambda a: a.id)

    def update(self, app: App) -> bool:
        with self._t.lock:
            if app.id not in self._t.rows:
                return False
            self._t.rows[app.id] = app
            return True

    def delete(self, app_id) -> bool:
        with self._t.lock:
            return self._t.rows.pop(int(app_id), None) is not None


class MemAccessKeys(base.AccessKeys):
    def __init__(self, config: Optional[dict] = None):
        self._rows: Dict[str, AccessKey] = {}
        self._lock = threading.RLock()

    def insert(self, k: AccessKey) -> Optional[str]:
        key = k.key or base.generate_access_key()
        with self._lock:
            self._rows[key] = AccessKey(key, k.appid, tuple(k.events))
        return key

    def get(self, key):
        return self._rows.get(key)

    def get_all(self):
        return list(self._rows.values())

    def get_by_appid(self, appid):
        return [k for k in self._rows.values() if k.appid == appid]

    def update(self, k: AccessKey) -> bool:
        with self._lock:
            if k.key not in self._rows:
                return False
            self._rows[k.key] = k
            return True

    def delete(self, key) -> bool:
        with self._lock:
            return self._rows.pop(key, None) is not None


class MemChannels(base.Channels):
    def __init__(self, config: Optional[dict] = None):
        self._t = _IdTable()

    def insert(self, c: Channel) -> Optional[int]:
        if not Channel.is_valid_name(c.name):
            return None
        with self._t.lock:
            if c.id:
                if c.id in self._t.rows:
                    return None  # explicit id conflict (matches sqlite)
                cid = c.id
            else:
                cid = next(self._t.next_id)
                while cid in self._t.rows:
                    cid = next(self._t.next_id)
            self._t.rows[cid] = Channel(cid, c.name, c.appid)
            return cid

    def get(self, channel_id):
        return self._t.rows.get(int(channel_id))

    def get_by_appid(self, appid):
        return [c for c in self._t.rows.values() if c.appid == appid]

    def delete(self, channel_id) -> bool:
        with self._t.lock:
            return self._t.rows.pop(int(channel_id), None) is not None


class MemEngineInstances(base.EngineInstances):
    def __init__(self, config: Optional[dict] = None):
        self._rows: Dict[str, EngineInstance] = {}
        self._counter = itertools.count(1)
        self._lock = threading.RLock()

    def insert(self, i: EngineInstance) -> str:
        with self._lock:
            iid = i.id or f"ei_{next(self._counter):08d}"
            import dataclasses as _dc
            self._rows[iid] = _dc.replace(i, id=iid)
            return iid

    def get(self, iid):
        return self._rows.get(iid)

    def get_all(self):
        return list(self._rows.values())

    def get_completed(self, engine_id, engine_version, engine_variant):
        rows = [
            r for r in self._rows.values()
            if r.status == "COMPLETED" and r.engine_id == engine_id
            and r.engine_version == engine_version
            and r.engine_variant == engine_variant
        ]
        rows.sort(key=lambda r: r.start_time, reverse=True)
        return rows

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        rows = self.get_completed(engine_id, engine_version, engine_variant)
        return rows[0] if rows else None

    def update(self, i: EngineInstance) -> bool:
        with self._lock:
            if i.id not in self._rows:
                return False
            self._rows[i.id] = i
            return True

    def delete(self, iid) -> bool:
        with self._lock:
            return self._rows.pop(iid, None) is not None


class MemEvaluationInstances(base.EvaluationInstances):
    def __init__(self, config: Optional[dict] = None):
        self._rows: Dict[str, EvaluationInstance] = {}
        self._counter = itertools.count(1)
        self._lock = threading.RLock()

    def insert(self, i: EvaluationInstance) -> str:
        with self._lock:
            iid = i.id or f"evi_{next(self._counter):08d}"
            import dataclasses as _dc
            self._rows[iid] = _dc.replace(i, id=iid)
            return iid

    def get(self, iid):
        return self._rows.get(iid)

    def get_all(self):
        return list(self._rows.values())

    def get_completed(self):
        rows = [r for r in self._rows.values() if r.status == "EVALCOMPLETED"]
        rows.sort(key=lambda r: r.start_time, reverse=True)
        return rows

    def update(self, i: EvaluationInstance) -> bool:
        with self._lock:
            if i.id not in self._rows:
                return False
            self._rows[i.id] = i
            return True

    def delete(self, iid) -> bool:
        with self._lock:
            return self._rows.pop(iid, None) is not None


class MemModels(base.Models):
    def __init__(self, config: Optional[dict] = None):
        self._rows: Dict[str, Model] = {}
        self._lock = threading.RLock()

    def insert(self, m: Model) -> None:
        with self._lock:
            self._rows[m.id] = m

    def get(self, mid):
        return self._rows.get(mid)

    def delete(self, mid) -> bool:
        with self._lock:
            return self._rows.pop(mid, None) is not None
