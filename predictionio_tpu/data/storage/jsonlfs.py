"""Partitioned JSON-lines event store — the scale-ingest backend.

Reference analog: the reference's bulk training reads are partitioned at
the storage layer — per time range on JDBC (``JDBCPEvents.scala:31-100``,
partition count = min(days, PARTITIONS)) and per region on HBase
(``HBPEvents.scala:83-89``) — so a 20M-event scan streams through
executors without ever being one object list. This backend is the
TPU-host equivalent: events live in append-only JSONL partition files
(rolled every ``part_max_events``), the native C++ codec decodes a whole
partition per call (including the numeric value column, so training
ingest builds zero per-event Python objects), and
``find_columnar_blocks`` streams one bounded columnar block per
partition straight into the padding pipeline.

Layout: ``<path>/app_<appid>_<channel>/part-<n>.jsonl`` with one event
JSON per line (the same wire format as export/import and the REST API —
``EventJson4sSupport.APISerializer`` parity via ``Event.to_json``).

Contracts:
- ``find``/``get``/``delete`` are the compatibility surface (admin and
  LEventStore paths): they parse typed Events and are O(store); the hot
  path is ``find_columnar_blocks``.
- ``delete`` rewrites the partition containing the event (append-only
  otherwise).
- Only the event DAOs exist — configure this source for EVENTDATA and
  keep METADATA/MODELDATA on sqlite/memory (the registry raises a clear
  error otherwise, mirroring ``Storage.scala``'s per-repository sources).
"""

from __future__ import annotations

import contextlib
import fcntl
import glob
import json
import logging
import os
import shutil
import threading
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from predictionio_tpu.data.aggregator import (
    AGGREGATOR_EVENT_NAMES,
    EntityState,
    fold_events,
    states_to_property_maps,
)
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import (
    Event,
    new_event_id,
    validate_event,
)
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import UNSET
from predictionio_tpu.data.storage.localfs import atomic_write_bytes
from predictionio_tpu.data.storage.memory import match_event
from predictionio_tpu.utils import metrics

DEFAULT_PART_MAX_EVENTS = 500_000
SNAPSHOT_NAME = "props_snapshot.json"

_log = logging.getLogger(__name__)


def _parse_event_line(raw: str, source: str) -> Optional[Event]:
    """A line that fails to parse is never a committed event — it is a
    torn fragment from a killed append (terminated by ``_repair_tail``)
    or external corruption. Skip it with a warning instead of letting one
    bad line poison every later read of the partition."""
    try:
        return Event.from_json(raw)
    except Exception:
        _log.warning("jsonlfs: skipping unparsable line in %s "
                     "(torn append fragment?)", source)
        return None


class JsonlFsLEvents(base.LEvents):
    """LEvents over partitioned JSONL files (one dir per app/channel)."""

    metrics_backend = "jsonlfs"

    def __init__(self, config: Optional[dict] = None):
        cfg = config or {}
        self._root = cfg.get("path") or os.path.join(
            os.getcwd(), ".pio_store", "events_jsonl")
        self._part_max = int(cfg.get("part_max_events",
                                     DEFAULT_PART_MAX_EVENTS))
        # dir -> [last_part_index, events_in_last_part, bytes_in_last_part]
        # (byte size validates the cache against other writers' appends)
        self._writers: dict = {}
        # dir -> {"watermark": {part_basename: byte_offset},
        #         "states": {etype: {eid: EntityState record}}} — the
        # entity-props snapshot cache (see materialized_aggregate)
        self._snapshots: dict = {}
        self._lock = threading.RLock()          # guards dicts only
        self._dir_tlocks: dict = {}             # dir -> threading.RLock

    # -- layout -----------------------------------------------------------

    def _dir(self, app_id: int, channel_id: Optional[int]) -> str:
        chan = -1 if channel_id is None else int(channel_id)
        return os.path.join(self._root, f"app_{int(app_id)}_{chan}")

    def _parts(self, d: str) -> List[str]:
        return sorted(glob.glob(os.path.join(d, "part-*.jsonl")))

    @contextlib.contextmanager
    def _dir_lock(self, d: str):
        """Mutual exclusion for one app/channel directory, across
        threads (per-directory RLock) AND processes (advisory flock on
        ``<dir>/.lock``), taken around every append and every partition
        rewrite so a CLI cleanup racing a live eventserver's appends can
        never drop freshly appended lines. The process-global ``_lock``
        is held only for dict access — one directory's long rewrite
        must not stall writes to other apps."""
        with self._lock:
            tlock = self._dir_tlocks.setdefault(d, threading.RLock())
        with tlock:
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, ".lock"), "a") as lf:
                fcntl.flock(lf.fileno(), fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lf.fileno(), fcntl.LOCK_UN)

    @staticmethod
    def _repair_tail(path: str) -> None:
        """Terminate a torn final line (killed mid-append): without this
        the next append would glue new JSON onto the fragment. Terminated,
        the fragment is its own (unparsable) line, which readers skip."""
        try:
            with open(path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")
        except FileNotFoundError:
            pass

    def _derive_state(self, d: str) -> list:
        """Last partition's [index, line count, byte size] from disk,
        repairing a torn tail first. Caller holds the directory lock; the
        global ``_lock`` is never taken here, so the (possibly large)
        recount never stalls writes to other apps."""
        parts = self._parts(d)
        if not parts:
            return [0, 0, 0]
        idx = int(os.path.basename(parts[-1])[5:-6])
        self._repair_tail(parts[-1])
        with open(parts[-1], "rb") as f:
            cnt = sum(chunk.count(b"\n") for chunk in
                      iter(lambda: f.read(1 << 20), b""))
        return [idx, cnt, os.path.getsize(parts[-1])]

    def _writer_state(self, d: str) -> list:
        """Caller must hold the DIRECTORY lock. The cached
        [part_idx, count, size] is validated against the partition's
        on-disk byte size on every call, so a second legal writer
        (eventserver + CLI import share the flock) can never leave this
        instance appending with a stale count and overfilling a part."""
        with self._lock:
            st = self._writers.get(d)
        if st is not None:
            path = os.path.join(d, f"part-{st[0]:05d}.jsonl")
            try:
                if os.path.getsize(path) == st[2]:
                    return st
            except OSError:
                pass  # partition vanished or never written: re-derive
        fresh = self._derive_state(d)
        with self._lock:
            st = self._writers.setdefault(d, fresh)
            if st is not fresh:
                st[:] = fresh
        return st

    # -- lifecycle --------------------------------------------------------

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        os.makedirs(self._dir(app_id, channel_id), exist_ok=True)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):
            return False
        with self._dir_lock(d):
            with self._lock:
                self._writers.pop(d, None)
                self._snapshots.pop(d, None)
            # let a failed deletion RAISE (a silent True would report
            # data deleted while partitions remain on disk); the .lock
            # file itself is part of the tree and goes with it
            shutil.rmtree(d)
            # the tail generation lives BESIDE the directory and so
            # survives this: a re-created scope re-issues the same
            # partition names, and enough re-ingest would push part
            # sizes past a pre-remove cursor's offsets — without the
            # bump that cursor would silently skip the re-landed events
            self._bump_tail_gen(d)
        return True

    def close(self) -> None:
        pass

    # -- writes -----------------------------------------------------------

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Iterable[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        evs = list(events)
        for e in evs:
            validate_event(e)
        ids = [e.event_id or new_event_id() for e in evs]
        self.append_raw_lines(
            [e.with_id(i).to_json() for e, i in zip(evs, ids)],
            app_id, channel_id)
        return ids

    def append_raw_lines(self, lines: Sequence[str], app_id: int,
                         channel_id: Optional[int] = None) -> None:
        """Data-plane fast lane (cf. ``SqliteLEvents.insert_raw_batch``):
        pre-validated, pre-serialized event JSON lines appended with
        partition rolling — the bulk-import path."""
        lines = list(lines)
        d = self._dir(app_id, channel_id)
        with self._dir_lock(d):
            st = self._writer_state(d)
            pos = 0
            while pos < len(lines):
                while st[1] >= self._part_max:
                    nxt = os.path.join(d, f"part-{st[0] + 1:05d}.jsonl")
                    # another writer may have rolled past this partition
                    # already — jump to the true last part in that case
                    st[:] = self._derive_state(d) if os.path.exists(nxt) \
                        else [st[0] + 1, 0, 0]
                room = self._part_max - st[1]
                chunk = lines[pos:pos + room]
                path = os.path.join(d, f"part-{st[0]:05d}.jsonl")
                payload = ("\n".join(chunk) + "\n").encode("utf-8")
                with open(path, "ab") as f:
                    f.write(payload)
                st[1] += len(chunk)
                st[2] += len(payload)
                pos += len(chunk)

    # -- reads ------------------------------------------------------------

    def _iter_events(self, d: str) -> Iterable[Event]:
        """All events of one app/channel, storage order, typed. An
        unterminated trailing line (a racing live append's partial flush)
        is not a committed event and is skipped without a lock; streaming
        (never the whole partition in memory)."""
        for part in self._parts(d):
            # errors="replace": a fragment torn mid-multibyte character
            # must not poison the whole partition with UnicodeDecodeError
            with open(part, "r", encoding="utf-8",
                      errors="replace") as f:
                for line in f:
                    if not line.endswith("\n"):
                        break  # in-flight append or torn crash fragment
                    line = line.strip()
                    if line:
                        e = _parse_event_line(line, part)
                        if e is not None:
                            yield e

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        for e in self._iter_events(self._dir(app_id, channel_id)):
            if e.event_id == event_id:
                return e
        return None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):  # nothing to delete; don't create dirs
            return False
        needle = f'"{event_id}"'
        with self._dir_lock(d):
            for part in self._parts(d):
                with open(part, "r", encoding="utf-8",
                          errors="replace") as f:
                    lines = f.readlines()

                def _is_target(ln: str) -> bool:
                    if needle not in ln:
                        return False
                    e = _parse_event_line(ln, part)
                    return e is not None and e.event_id == event_id

                kept = [ln for ln in lines if not _is_target(ln)]
                if len(kept) != len(lines):
                    # atomic replace (as delete_until): a crash
                    # mid-rewrite must never lose the surviving events
                    tmp = part + ".tmp"
                    with open(tmp, "w", encoding="utf-8") as f:
                        f.writelines(kept)
                    os.replace(tmp, part)
                    with self._lock:
                        self._writers.pop(d, None)  # recount on append
                    self._invalidate_snapshot(d)  # offsets now meaningless
                    return True
        return False

    def delete_until(self, app_id, until_time, channel_id=None) -> int:
        """Rewrite each partition keeping only post-cutoff lines (the
        native codec supplies per-line times + byte spans, so surviving
        lines are copied verbatim without re-serialization)."""
        from predictionio_tpu.native import codec

        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):  # nothing to clean; don't create dirs
            return 0
        cutoff = until_time.timestamp()
        removed = 0
        with self._dir_lock(d):
            for part in self._parts(d):
                with open(part, "rb") as f:
                    data = f.read()
                parsed = codec.parse_jsonl(data, columns=set())
                if parsed is None:
                    kept, dropped = self._filter_lines_python(data, cutoff)
                else:
                    times = parsed.event_time.copy()
                    for i in np.nonzero(np.isnan(times))[0]:
                        raw = data[parsed.line_start[i]:
                                   parsed.line_end[i]].decode(
                            "utf-8", errors="replace").strip()
                        e = _parse_event_line(raw, part)
                        # unparsable torn fragments get dropped by the
                        # rewrite along with the pre-cutoff events
                        times[i] = e.event_time.timestamp() \
                            if e is not None else float("-inf")
                    keep = times >= cutoff
                    kept = [data[parsed.line_start[i]:parsed.line_end[i]]
                            for i in np.nonzero(keep)[0]]
                    dropped = int((~keep).sum())
                if dropped:
                    # atomic replace: a crash mid-rewrite must never lose
                    # the surviving (post-cutoff) events
                    tmp = part + ".tmp"
                    with open(tmp, "wb") as f:
                        if kept:
                            f.write(b"\n".join(kept))
                            f.write(b"\n")
                    os.replace(tmp, part)
                    removed += dropped
            with self._lock:
                self._writers.pop(d, None)  # recount on next append
            if removed:
                self._invalidate_snapshot(d)  # offsets now meaningless
        return removed

    def _filter_lines_python(self, data: bytes, cutoff: float):
        kept: List[bytes] = []
        dropped = 0
        for line in data.split(b"\n"):
            if not line.strip():
                continue
            e = _parse_event_line(line.decode("utf-8", errors="replace"),
                                  "delete_until")
            if e is None:
                dropped += 1
            elif e.event_time.timestamp() >= cutoff:
                kept.append(line)
            else:
                dropped += 1
        return kept, dropped

    def find(self, app_id, channel_id=None, start_time=None, until_time=None,
             entity_type=None, entity_id=None, event_names=None,
             target_entity_type=UNSET, target_entity_id=UNSET,
             limit=None, reversed=False) -> Iterable[Event]:
        out = [e for e in self._iter_events(self._dir(app_id, channel_id))
               if match_event(e, start_time, until_time, entity_type,
                              entity_id, event_names, target_entity_type,
                              target_entity_id)]
        out.sort(key=lambda e: e.event_time, reverse=bool(reversed))
        if limit is not None and limit >= 0:
            out = out[:limit]
        return iter(out)

    # -- tail reads (find_since contract, base.py) -------------------------
    # The cursor IS a per-partition byte watermark — the same shape the
    # PR-1 materialized-aggregation snapshot records (``_delta_lines``),
    # reused here as a consumer-owned position: arrival order is file
    # order, unterminated tails are never consumed (their offset stays
    # before them), and a partition rewrite (delete/delete_until) that
    # moved bytes under the offsets resets the cursor to a full replay.
    # Rewrites are detected two ways: a partition now SHORTER than its
    # recorded offset, and a per-directory rewrite generation carried in
    # the cursor — the latter catches a rewrite whose partition has
    # since been appended back past the stale offset (names survive
    # rewrites, so sizes alone cannot prove the bytes under an offset
    # are the ones the cursor consumed).

    @staticmethod
    def _gen_path(d: str) -> str:
        # a SIBLING of the scope directory, not inside it: remove()
        # deletes the whole tree, and the generation must survive a
        # remove + re-init (same partition names come back)
        return d.rstrip(os.sep) + ".tail_gen"

    def _tail_gen(self, d: str) -> int:
        try:
            with open(self._gen_path(d), "r", encoding="ascii") as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _bump_tail_gen(self, d: str) -> None:
        """Caller holds the directory lock (rewrite/remove paths only)."""
        try:
            atomic_write_bytes(self._gen_path(d),
                               str(self._tail_gen(d) + 1).encode("ascii"))
        except OSError:
            # a read-only tree cannot be rewritten either, so there is
            # no offset movement to signal
            pass

    @staticmethod
    def _complete_size(path: str) -> int:
        """Byte offset just past the last COMPLETE (newline-terminated)
        line — the tail-cursor boundary: an offset inside a torn or
        in-flight final line would make the next read start mid-line
        and silently lose that event once it completes."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return 0
        if size == 0:
            return 0
        with open(path, "rb") as f:
            f.seek(size - 1)
            if f.read(1) == b"\n":
                return size
            end = size - 1
            chunk = 1 << 16
            while end > 0:
                start = max(0, end - chunk)
                f.seek(start)
                data = f.read(end - start)
                cut = data.rfind(b"\n")
                if cut >= 0:
                    return start + cut + 1
                end = start
        return 0

    def find_since(self, app_id, channel_id=None, cursor=None, limit=None):
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):
            return [], {"kind": "jsonlfs", "watermark": {}, "gen": 0}
        wm = dict((cursor or {}).get("watermark", {}) or {})
        events: List[Event] = []
        with self._dir_lock(d):
            gen = self._tail_gen(d)
            parts = self._parts(d)
            names = {os.path.basename(p) for p in parts}
            stale = wm and (
                int((cursor or {}).get("gen", 0)) != gen
                or any(n not in names
                       or os.path.getsize(os.path.join(d, n)) < int(off)
                       for n, off in wm.items()))
            if stale:
                # a rewrite moved bytes under the offsets: replay from
                # the start (replay-tolerant consumer contract)
                wm = {}
            new_wm = dict(wm)
            full = False
            for part in parts:
                name = os.path.basename(part)
                off = int(wm.get(name, 0))
                end = self._complete_size(part)
                if end > off:
                    with open(part, "rb") as f:
                        f.seek(off)
                        data = f.read(end - off)
                    consumed = 0
                    for raw in data.split(b"\n")[:-1]:
                        if limit is not None and len(events) >= int(limit):
                            full = True
                            break
                        consumed += len(raw) + 1
                        raw = raw.strip()
                        if raw:
                            e = _parse_event_line(
                                raw.decode("utf-8", errors="replace"),
                                part)
                            if e is not None:
                                events.append(e)
                    off += consumed
                new_wm[name] = off
                if full:
                    break
        return events, {"kind": "jsonlfs", "watermark": new_wm,
                        "gen": gen}

    def tail_cursor(self, app_id, channel_id=None):
        d = self._dir(app_id, channel_id)
        wm: Dict[str, int] = {}
        gen = 0
        if os.path.isdir(d):
            with self._dir_lock(d):
                gen = self._tail_gen(d)
                for part in self._parts(d):
                    wm[os.path.basename(part)] = self._complete_size(part)
        return {"kind": "jsonlfs", "watermark": wm, "gen": gen}

    def tail_watermark(self, app_id, channel_id=None):
        d = self._dir(app_id, channel_id)
        out = {"cursor": {"kind": "jsonlfs", "watermark": {}, "gen": 0},
               "lastEventId": None, "lastEventTime": None}
        if not os.path.isdir(d):
            return out
        last: Optional[Event] = None
        with self._dir_lock(d):
            out["cursor"]["gen"] = self._tail_gen(d)
            parts = self._parts(d)
            wm = {os.path.basename(p): self._complete_size(p)
                  for p in parts}
            for part in reversed(parts):
                end = wm[os.path.basename(part)]
                if end == 0:
                    continue
                # scan back in doubling windows: a window that starts
                # mid-line truncates its first line into an unparsable
                # fragment, so a single fixed-size window would report
                # a STALE watermark whenever the final event line is
                # bigger than it (large properties payloads)
                window = 1 << 16
                with open(part, "rb") as f:
                    while last is None:
                        start = max(0, end - window)
                        f.seek(start)
                        data = f.read(end - start)
                        lines = [ln for ln in data.split(b"\n")
                                 if ln.strip()]
                        if start > 0:
                            lines = lines[1:]  # possibly torn head
                        for raw in reversed(lines):
                            e = _parse_event_line(
                                raw.decode("utf-8", errors="replace"),
                                part)
                            if e is not None:
                                last = e
                                break
                        if start == 0:
                            break
                        window *= 2
                if last is not None:
                    break
        out["cursor"]["watermark"] = wm
        if last is not None:
            out["lastEventId"] = last.event_id
            out["lastEventTime"] = last.event_time.isoformat()
        return out

    # -- materialized entity-property state (watermark snapshot) ----------

    def _invalidate_snapshot(self, d: str) -> None:
        """A partition rewrite moved bytes under the recorded offsets —
        drop the snapshot so the next read refolds from scratch, and
        bump the tail generation so outstanding tail cursors reset to a
        full replay (partition names survive a rewrite, so a shrink
        followed by enough appends could otherwise push the file back
        past a stale byte offset and silently skip the re-landed
        bytes). Caller holds the directory lock."""
        self._bump_tail_gen(d)
        with self._lock:
            self._snapshots.pop(d, None)
        try:
            os.unlink(os.path.join(d, SNAPSHOT_NAME))
            metrics.AGGREGATE_SCOPE_DROPS.inc(backend=self.metrics_backend)
        except FileNotFoundError:
            pass

    def _load_snapshot(self, d: str) -> dict:
        with self._lock:
            snap = self._snapshots.get(d)
        if snap is not None and os.path.exists(os.path.join(d,
                                                            SNAPSHOT_NAME)):
            # the existence check guards against ANOTHER process having
            # invalidated (unlinked) the snapshot after a partition
            # rewrite — our in-memory cache would otherwise survive a
            # rewrite whose file later grows back past the cached offsets
            return snap
        try:
            with open(os.path.join(d, SNAPSHOT_NAME), "r",
                      encoding="utf-8") as f:
                snap = json.load(f)
            if not isinstance(snap, dict) \
                    or not isinstance(snap.get("watermark"), dict) \
                    or not isinstance(snap.get("states"), dict):
                raise ValueError("malformed snapshot")
        except (FileNotFoundError, ValueError, json.JSONDecodeError):
            snap = {"watermark": {}, "states": {}}
        return snap

    def _delta_lines(self, d: str, parts: List[str],
                     watermark: Dict[str, int]):
        """Complete lines appended past the watermark, in file order, plus
        the advanced watermark. Unterminated tails (in-flight appends) are
        not consumed — their offset stays before them."""
        new_mark: Dict[str, int] = {}
        lines: List[str] = []
        for part in parts:
            name = os.path.basename(part)
            off = int(watermark.get(name, 0))
            size = os.path.getsize(part)
            if size > off:
                with open(part, "rb") as f:
                    f.seek(off)
                    data = f.read(size - off)
                cut = data.rfind(b"\n") + 1
                for raw in data[:cut].split(b"\n"):
                    raw = raw.strip()
                    if raw:
                        lines.append(raw.decode("utf-8", errors="replace"))
                off += cut
            new_mark[name] = off
        return lines, new_mark

    def materialized_aggregate(self, app_id, entity_type, channel_id=None
                               ) -> Optional[Dict[str, PropertyMap]]:
        """Serve ``aggregate_properties`` current-state reads from a
        watermark snapshot: the fold up to the watermark is persisted in
        ``props_snapshot.json`` (atomic write), and a read replays only
        the bytes appended since — O(delta), not O(store). Partition
        rewrites (delete/delete_until) invalidate the snapshot; an
        out-of-order append re-derives just the touched entities."""
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):
            return {}
        try:
            with self._dir_lock(d):
                snap = self._load_snapshot(d)
                parts = self._parts(d)
                names = {os.path.basename(p) for p in parts}
                stale = [n for n, off in snap["watermark"].items()
                         if n not in names
                         or os.path.getsize(os.path.join(d, n)) < off]
                if stale:
                    # a rewrite slipped past invalidation (another
                    # process): offsets are meaningless, refold everything
                    snap = {"watermark": {}, "states": {}}
                fresh = not snap["watermark"]
                lines, new_mark = self._delta_lines(d, parts,
                                                    snap["watermark"])
                if lines or new_mark != snap["watermark"]:
                    if fresh:
                        # folding the whole store, not a delta — the
                        # jsonlfs analog of the sqlite scope backfill
                        metrics.AGGREGATE_BACKFILLS.inc(
                            backend=self.metrics_backend)
                    delta: List[Event] = []
                    for ln in lines:
                        # cheap prefilter: a special event's JSON must
                        # spell its name either literally ('"$set"') or
                        # with the dollar sign escaped as '\\u0024' (raw
                        # client lines arrive verbatim) — skip full
                        # parses for the (dominant) non-special traffic,
                        # never for a possibly-special line
                        if '"$' not in ln and '\\u0024' not in ln:
                            continue
                        e = _parse_event_line(ln, d)
                        if e is not None and \
                                e.event in AGGREGATOR_EVENT_NAMES:
                            delta.append(e)
                    self._fold_delta(d, snap, delta)
                    snap["watermark"] = new_mark
                    atomic_write_bytes(
                        os.path.join(d, SNAPSHOT_NAME),
                        json.dumps(snap, sort_keys=True).encode("utf-8"))
                with self._lock:
                    self._snapshots[d] = snap
                # extract under the dir lock: a concurrent reader's delta
                # fold mutates these dicts in place
                states = {eid: EntityState.from_record(rec)
                          for eid, rec in snap["states"]
                          .get(entity_type, {}).items()}
        except OSError:
            # read-only events directory (snapshot/.lock writes refused)
            # or fs trouble: stay servable via the pure-read replay
            return None
        return states_to_property_maps(states)

    def _fold_delta(self, d: str, snap: dict, delta: List[Event]) -> None:
        by_entity: Dict[tuple, List[Event]] = {}
        for e in delta:
            by_entity.setdefault((e.entity_type, e.entity_id), []).append(e)
        out_of_order: List[tuple] = []
        for (etype, eid), evs in by_entity.items():
            recs = snap["states"].setdefault(etype, {})
            rec = recs.get(eid)
            st = None if rec is None else EntityState.from_record(rec)
            if st is not None and st.last_updated is not None and \
                    min(e.event_time for e in evs) < st.last_updated:
                # replay would sort these before already-folded events
                out_of_order.append((etype, eid))
                continue
            recs[eid] = fold_events(evs, st).to_record()
        if out_of_order:
            # one full pass re-deriving ONLY the out-of-order entities
            wanted = set(out_of_order)
            history: Dict[tuple, List[Event]] = {k: [] for k in wanted}
            for e in self._iter_events(d):
                k = (e.entity_type, e.entity_id)
                if k in history and e.event in AGGREGATOR_EVENT_NAMES:
                    history[k].append(e)
            for (etype, eid), evs in history.items():
                recs = snap["states"].setdefault(etype, {})
                st = fold_events(evs)
                if st is None:
                    recs.pop(eid, None)
                else:
                    recs[eid] = st.to_record()


class JsonlFsPEvents(base.LEventsBackedPEvents):
    """Bulk reads: native-codec partition scans streaming columnar blocks."""

    def __init__(self, config: Optional[dict] = None):
        super().__init__(JsonlFsLEvents(config))

    # -- streaming columnar scan (the scale path) -------------------------

    def find_columnar_blocks(self, app_id, channel_id=None, start_time=None,
                             until_time=None, entity_type=None,
                             event_names=None, target_entity_type=UNSET,
                             value_property=None, default_value=1.0,
                             strict=True, block_size=1_000_000,
                             prefetch=0):
        """One bounded :class:`ColumnarEvents` block per partition file
        (further split at ``block_size``), in storage order. Each
        partition is decoded in one native-codec pass — value column
        included — so peak host memory is one partition's columns, never
        the whole store.

        ``prefetch`` > 0 is the block-prefetch hint: up to that many
        partitions are read AND decoded ahead on a small thread pool
        (the C++ codec releases the GIL, so the decodes genuinely run
        in parallel), while blocks still yield in exact storage order —
        the pipelined-ingest decode stage stops being one partition
        deep. Peak memory rises to ``prefetch`` decoded partitions.
        0 keeps the serial one-partition-at-a-time scan."""
        lev: JsonlFsLEvents = self._l
        d = lev._dir(app_id, channel_id)
        kw = dict(start_time=start_time, until_time=until_time,
                  entity_type=entity_type, event_names=event_names,
                  target_entity_type=target_entity_type,
                  value_property=value_property,
                  default_value=default_value, strict=strict)
        parts = lev._parts(d)
        if prefetch and len(parts) > 1:
            import collections
            from concurrent.futures import ThreadPoolExecutor

            window = max(1, int(prefetch))
            ex = ThreadPoolExecutor(max_workers=window,
                                    thread_name_prefix="pio-part-decode")
            try:
                pending = collections.deque(
                    ex.submit(self._read_decode_part, p, kw)
                    for p in parts[:window])
                nxt = window
                while pending:
                    blocks = pending.popleft().result()  # storage order
                    if nxt < len(parts):
                        pending.append(ex.submit(self._read_decode_part,
                                                 parts[nxt], kw))
                        nxt += 1
                    for block in blocks:
                        for i in range(0, len(block), block_size):
                            yield block.take(slice(i, i + block_size))
            finally:
                # early consumer exit / poisoned-part error: don't
                # block teardown on in-flight whole-partition decodes —
                # cancel the queued ones and let running ones finish in
                # the background (their results are dropped)
                ex.shutdown(wait=False, cancel_futures=True)
            return
        for part in parts:
            for block in self._read_decode_part(part, kw):
                for i in range(0, len(block), block_size):
                    yield block.take(slice(i, i + block_size))

    def _read_decode_part(self, part: str, kw: dict):
        """Read one partition's bytes and decode them to blocks — the
        unit the prefetch pool parallelizes."""
        with open(part, "rb") as f:
            data = f.read()
        if data and not data.endswith(b"\n"):
            # an unterminated tail is a racing live append's partial
            # flush (or a torn crash fragment) — not a committed
            # event; scan only the complete lines
            data = data[:data.rfind(b"\n") + 1]
        # a part may yield TWO blocks: the (encoded) bulk of the
        # file plus a small object-form block of fallback rows — one
        # exotic line must not de-optimize the whole partition
        return self._decode_part(data, source=part, **kw)

    def find_columnar(self, app_id, channel_id=None, start_time=None,
                      until_time=None, entity_type=None, event_names=None,
                      target_entity_type=UNSET, value_property=None,
                      default_value=1.0, strict=True):
        """Full scan = concatenated blocks, stably sorted by event time
        (the non-streaming contract other backends honor)."""
        from predictionio_tpu.data.columnar import ColumnarEvents

        blocks = list(self.find_columnar_blocks(
            app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names, target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict))
        batch = ColumnarEvents.concat(blocks)
        order = np.argsort(batch.event_times, kind="stable")
        return batch.take(order)

    def _decode_part(self, data: bytes, *, start_time, until_time,
                     entity_type, event_names, target_entity_type,
                     value_property, default_value, strict, source: str):
        return decode_jsonl_events(
            data, start_time=start_time, until_time=until_time,
            entity_type=entity_type, event_names=event_names,
            target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict, source=source)


def decode_jsonl_events(data: bytes, *, start_time=None, until_time=None,
                        entity_type=None, event_names=None,
                        target_entity_type=UNSET, value_property=None,
                        default_value=1.0, strict=True,
                        source: str = "<bytes>"):
    """Event-JSONL bytes -> list of filtered ColumnarEvents, native codec
    first. The string columns come back DICTIONARY-ENCODED (int32 codes +
    distinct labels), so filtering is pure numpy over codes and no
    per-event Python strings exist — the 10M-row fast lane. Fallback
    rows (lines the codec punted on) come back as a separate small
    object-form block so they never de-optimize the encoded bulk.

    Shared by the jsonlfs partition scan and the resthttp client (which
    ships partition bytes over the wire and decodes them here)."""
    from predictionio_tpu.data.columnar import (
        ColumnarEvents,
        events_to_columnar,
    )
    from predictionio_tpu.native import codec

    enc = {codec.COL_EVENT, codec.COL_ENTITY_ID,
           codec.COL_TARGET_ENTITY_ID}
    # type columns are only worth an O(n) encode pass when their
    # filters are active
    if entity_type is not None:
        enc.add(codec.COL_ENTITY_TYPE)
    if target_entity_type is not UNSET:
        enc.add(codec.COL_TARGET_ENTITY_TYPE)
    parsed = codec.parse_jsonl(
        data, numeric_property=value_property, dict_encode=enc,
        # the only per-row strings materialized: raw eventTime text,
        # needed just for rows whose time the C++ parser punted on
        columns={codec.COL_EVENT_TIME_RAW})
    if parsed is None:  # no native lib: python oracle on the whole part
        events = [e for ln in data.decode("utf-8").splitlines()
                  if ln.strip()
                  and (e := _parse_event_line(ln, source)) is not None]
        kept = [e for e in events
                if match_event(e, start_time, until_time, entity_type,
                               None, event_names, target_entity_type,
                               UNSET)]
        return [events_to_columnar(kept, value_property=value_property,
                                   default_value=default_value,
                                   strict=strict)]

    flags = parsed.flags
    keep = (flags & codec.FALLBACK) == 0

    def code_filter(col: int, wanted: set) -> np.ndarray:
        """Rows whose encoded column value is in ``wanted`` — a label
        scan over the (tiny) distinct set + one vector isin."""
        labels = parsed.dict_labels[col]
        codes = parsed.dict_codes[col]
        want = np.asarray([j for j, lab in enumerate(labels)
                           if lab in wanted], dtype=np.int32)
        return np.isin(codes, want)

    if event_names is not None:
        keep &= code_filter(codec.COL_EVENT, set(event_names))
    if entity_type is not None:
        keep &= code_filter(codec.COL_ENTITY_TYPE, {entity_type})
    if target_entity_type is not UNSET:
        tet = parsed.dict_codes[codec.COL_TARGET_ENTITY_TYPE]
        if target_entity_type is None:
            keep &= tet == -1
        else:
            keep &= code_filter(codec.COL_TARGET_ENTITY_TYPE,
                                {target_entity_type})

    times = parsed.event_time.copy()
    # rows the codec parsed but whose eventTime it could not (rare
    # exotic formats): resolve via the python parser so time filters
    # and ordering stay exact
    nan_rows = np.nonzero(keep & np.isnan(times))[0]
    if len(nan_rows):
        from predictionio_tpu.data.event import _now, _parse_time

        now_ts = _now().timestamp()
        for i in nan_rows:
            raw = parsed.event_time_raw[i]
            t = _parse_time(raw) if raw is not None else None
            times[i] = t.timestamp() if t is not None else now_ts
    if start_time is not None:
        keep &= times >= start_time.timestamp()
    if until_time is not None:
        keep &= times < until_time.timestamp()

    idx = np.nonzero(keep)[0]
    vals = np.full(len(idx), float(default_value), dtype=np.float32)
    if value_property is not None and len(idx):
        status = parsed.prop_status[idx]
        if strict and (status == 2).any():
            bad = idx[int(np.nonzero(status == 2)[0][0])]
            raise ValueError(
                f"property {value_property!r} of event at "
                f"{source}:{int(parsed.lineno[bad])} is non-numeric")
        numeric = status == 1
        vals[numeric] = parsed.prop_value[idx][numeric].astype(
            np.float32)
    block = ColumnarEvents(
        entity_ids=None,
        target_ids=None,
        values=vals,
        event_times=times[idx],
        entity_codes=parsed.dict_codes[codec.COL_ENTITY_ID][idx],
        entity_labels=parsed.dict_labels[codec.COL_ENTITY_ID],
        target_codes=parsed.dict_codes[
            codec.COL_TARGET_ENTITY_ID][idx],
        target_labels=parsed.dict_labels[codec.COL_TARGET_ENTITY_ID],
        event_codes=parsed.dict_codes[codec.COL_EVENT][idx],
        event_labels=parsed.dict_labels[codec.COL_EVENT],
    )

    out = [block]
    # fallback rows: the python oracle re-parses those exact lines
    # into their own small block
    fb_rows = np.nonzero((flags & codec.FALLBACK) != 0)[0]
    if len(fb_rows):
        events = []
        for i in fb_rows:
            raw = data[parsed.line_start[i]:parsed.line_end[i]] \
                .decode("utf-8", errors="replace").strip()
            e = _parse_event_line(raw, source)
            if e is None:
                continue
            if match_event(e, start_time, until_time, entity_type,
                           None, event_names, target_entity_type,
                           UNSET):
                events.append(e)
        if events:
            out.append(events_to_columnar(
                events, value_property=value_property,
                default_value=default_value, strict=strict))
    return out
