"""Storage DAO contracts + metadata records.

Parity targets:
- ``LEvents`` trait (reference ``data/.../storage/LEvents.scala:76-328``):
  CRUD + filtered find + property aggregation over one app/channel. The
  reference exposes Future-based and blocking variants; our servers use
  threads + sqlite/memory backends, so the blocking API is canonical and
  async wrappers live at the server layer.
- ``PEvents`` (``PEvents.scala:77-181``): bulk reads for training. Spark
  RDDs are replaced by list/numpy columnar batches — the TPU ingest format.
- Metadata records: ``Apps.scala``, ``AccessKeys.scala`` (48-byte secure
  keygen, :65-70), ``Channels.scala`` (name regex, :51-54),
  ``EngineInstances.scala:43-59`` (15 fields), ``EvaluationInstances.scala``,
  ``Models.scala:30-49``.
"""

from __future__ import annotations

import abc
import base64
import dataclasses
import datetime as _dt
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from predictionio_tpu.data.aggregator import aggregate_properties
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.utils import metrics, resilience

# Sentinel distinguishing "no filter" from "filter for None"
# (reference models this as Option[Option[String]], LEvents.scala:137-150).
UNSET = object()


class StorageError(RuntimeError):
    pass


class StorageCircuitOpen(StorageError,
                         resilience.CircuitOpenError):
    """The storage endpoint's circuit breaker refused the call —
    BOTH a ``StorageError`` (callers treating "storage is down"
    uniformly, e.g. ``pio status``, catch it) and a
    ``CircuitOpenError`` (resilience-aware callers read the breaker
    semantics and the ``pio_retry_after`` hint)."""

    def __init__(self, endpoint: str, retry_in: float):
        resilience.CircuitOpenError.__init__(self, endpoint, retry_in)

    @classmethod
    def from_error(cls, e: "resilience.CircuitOpenError"
                   ) -> "StorageCircuitOpen":
        return cls(e.endpoint, getattr(e, "pio_retry_after", 0.0))


def run_guarded(breaker: "resilience.CircuitBreaker",
                policy: "resilience.RetryPolicy",
                attempt_fn, *, idempotent: Any = True,
                on_retry=None, defer_success: bool = False):
    """The breaker + retry shell shared by the DAO wrapper
    (``observed.DAOMetricsWrapper``) and the resthttp ``_Wire``: gate
    on the breaker (an open circuit surfaces as
    :class:`StorageCircuitOpen` so "storage is down" handlers catch
    it), run ``attempt_fn`` under the retry policy, feed the final
    outcome back to the breaker. ``defer_success`` skips the success
    mark — for lazy ops (``find`` returning a generator) the CALLER
    records the outcome when iteration ends, so generator creation
    cannot masquerade as a healthy read."""
    try:
        breaker.before_call()
    except resilience.CircuitOpenError as e:
        raise StorageCircuitOpen.from_error(e) from None
    try:
        result = policy.run(attempt_fn, idempotent=idempotent,
                            on_retry=on_retry)
    except BaseException as e:
        breaker.record_failure(e)
        raise
    if not defer_success:
        breaker.record_success()
    return result


class LEvents(abc.ABC):
    """Event store DAO scoped by (app_id, channel_id)."""

    # label value for this backend's storage/aggregation metrics;
    # concrete backends override (memory/sqlite/jsonlfs/resthttp)
    metrics_backend = "unknown"

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Initialize the backing store for one app/channel (LEvents.scala:87)."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Drop all events of one app/channel (LEvents.scala:95)."""

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        """Insert; returns the assigned event ID (futureInsert parity)."""

    def insert_batch(self, events: Iterable[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        """Bulk insert; returns assigned IDs in order. Backends override with
        a transactional fast path (the TPU ingest path needs the throughput;
        no single reference analog — closest is PEvents.write)."""
        return [self.insert(e, app_id, channel_id) for e in events]

    @abc.abstractmethod
    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]: ...

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool: ...

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterable[Event]:
        """Filtered scan ordered by event_time (LEvents.scala:118-176).

        ``limit=None`` or ``-1`` means no limit. ``reversed=True`` returns
        descending event time (only sensible with entity filters, as in the
        reference).
        """

    # -- tail reads (online fold-in, PR 8) ---------------------------------
    #
    # The cursor is an opaque JSON-safe dict each backend mints for its
    # own notion of arrival order (memory: insertion sequence; sqlite:
    # rowid; jsonlfs: the per-partition byte watermark the PR-1
    # materialized-aggregation deltas introduced; resthttp: whatever the
    # remote server's backend mints, passed through verbatim). Contract:
    # every event APPENDED after the cursor was minted is delivered by a
    # later find_since exactly once in arrival order; a store rewrite
    # (remove / delete_until / partition rewrite) may invalidate a
    # cursor, in which case the backend RESETS and replays from the
    # start — consumers must be replay-tolerant (the fold-in consumer
    # is: it re-gathers full per-user state, so a replay is wasted work,
    # never wrong results).

    def find_since(self, app_id: int, channel_id: Optional[int] = None,
                   cursor: Optional[Dict] = None,
                   limit: Optional[int] = None
                   ) -> Tuple[List[Event], Dict]:
        """Events appended after ``cursor`` (``None`` = from the start)
        in arrival order, plus the advanced cursor. ``limit`` bounds one
        call; the returned cursor resumes exactly after the last
        delivered event."""
        raise StorageError(
            f"{type(self).__name__} does not support tail reads "
            "(find_since)")

    def tail_cursor(self, app_id: int,
                    channel_id: Optional[int] = None) -> Dict:
        """A cursor at the CURRENT end of the stream — what a consumer
        that only wants future events starts from (O(1)-ish; never a
        store scan)."""
        raise StorageError(
            f"{type(self).__name__} does not support tail reads "
            "(tail_cursor)")

    def tail_watermark(self, app_id: int,
                       channel_id: Optional[int] = None
                       ) -> Optional[Dict]:
        """Observability view of the stream end: ``{"cursor": ...,
        "lastEventId": ..., "lastEventTime": ...}`` (id/time ``None``
        for an empty scope), or ``None`` when the backend keeps no
        cheap notion of it. Surfaced per (app, channel) by the event
        server's ``GET /stats.json`` — the freshness hook the online
        fold-in story needs."""
        return None

    def delete_until(self, app_id: int, until_time: _dt.datetime,
                     channel_id: Optional[int] = None) -> int:
        """Bulk-remove every event with event_time < until_time; returns
        the count removed. This is the cleanup-app capability
        (``examples/experimental/scala-cleanup-app/.../DataSource.scala``
        deletes pre-cutoff events one futureDelete at a time); backends
        override with single-pass bulk paths."""
        ids = [e.event_id for e in self.find(
            app_id=app_id, channel_id=channel_id, until_time=until_time)]
        n = 0
        for eid in ids:
            if eid and self.delete(eid, app_id, channel_id):
                n += 1
        return n

    def materialized_aggregate(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
    ) -> Optional[Dict[str, PropertyMap]]:
        """Serve the unbounded "state now" aggregation from materialized
        state, or return ``None`` when this backend keeps none (the
        caller then falls back to :meth:`aggregate_properties_replay`).
        An EMPTY scope with materialized support returns ``{}``, never
        ``None``. Backends maintain this state write-through at insert
        (sqlite/memory) or as a watermark snapshot + delta replay
        (jsonlfs); semantics are bit-identical to the replay fold."""
        return None

    def aggregate_properties_replay(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """The O(event history) fold over a filtered scan — the reference
        semantics (LEvents.scala:191-214) and the oracle the materialized
        path is differentially tested against."""
        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=list(aggregate_event_names()),
        )
        return _apply_required(aggregate_properties(events), required)

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Fold special events into per-entity property state
        (LEvents.scala:191-214).

        The unbounded call — the shape every template training read
        issues — is served from materialized state when the backend
        keeps it (O(current entities) instead of O(event history)); any
        ``start_time``/``until_time`` bound falls back to the replay
        fold so time-travel semantics stay exact. Every read is
        accounted in the metrics registry: a materialized hit, a
        ``bounded`` replay (time-travel query) or a ``fallback`` replay
        (backend keeps no state / its state was unreachable)."""
        if start_time is None and until_time is None:
            result = self.materialized_aggregate(app_id, entity_type,
                                                 channel_id)
            if result is not None:
                metrics.AGGREGATE_HITS.inc(backend=self.metrics_backend)
                return _apply_required(result, required)
            metrics.AGGREGATE_REPLAYS.inc(backend=self.metrics_backend,
                                          reason="fallback")
        else:
            metrics.AGGREGATE_REPLAYS.inc(backend=self.metrics_backend,
                                          reason="bounded")
        return self.aggregate_properties_replay(
            app_id, entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)


def _apply_required(result: Dict[str, PropertyMap],
                    required: Optional[Sequence[str]]) -> Dict[str, PropertyMap]:
    if not required:
        return result
    req = list(required)
    return {k: v for k, v in result.items() if all(r in v for r in req)}


def aggregate_event_names() -> Tuple[str, str, str]:
    return ("$set", "$unset", "$delete")


class PEvents(abc.ABC):
    """Bulk event reads for training (PEvents.scala:77-181).

    Returns full in-memory lists (a training host reads whole apps); the
    TPU data plane columnizes these into numpy batches for device_put.
    """

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
    ) -> List[Event]: ...

    @abc.abstractmethod
    def write(self, events: Iterable[Event], app_id: int,
              channel_id: Optional[int] = None) -> None: ...

    @abc.abstractmethod
    def delete(self, event_ids: Iterable[str], app_id: int,
               channel_id: Optional[int] = None) -> None: ...

    def find_columnar(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        value_property: Optional[str] = None,
        default_value: float = 1.0,
        strict: bool = True,
    ):
        """Bulk scan as a struct-of-arrays batch — the TPU ingest format
        (see ``predictionio_tpu.data.columnar``). Default implementation
        materializes Events then columnizes; backends override with a
        native scan that never builds per-row Python objects."""
        from predictionio_tpu.data.columnar import events_to_columnar

        return events_to_columnar(
            self.find(app_id=app_id, channel_id=channel_id,
                      start_time=start_time, until_time=until_time,
                      entity_type=entity_type, event_names=event_names,
                      target_entity_type=target_entity_type),
            value_property=value_property, default_value=default_value,
            strict=strict)

    def find_columnar_blocks(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        value_property: Optional[str] = None,
        default_value: float = 1.0,
        strict: bool = True,
        block_size: int = 1_000_000,
        prefetch: int = 0,
    ):
        """Streaming bulk scan: yields :class:`ColumnarEvents` blocks of at
        most ``block_size`` rows, in STORAGE order (not time order) — the
        scale-ingest contract (the reference partitions bulk reads the same
        way: per time range ``JDBCPEvents.scala:31-100``, per HBase region
        ``HBPEvents.scala:83-89``). Backends override so a block's memory
        is bounded; this default slices one materialized scan and only
        bounds what downstream consumers hold.

        ``prefetch`` is a read-ahead HINT (how many storage units the
        backend may read/decode ahead of the consumer, trading memory
        for decode parallelism); backends without a natural unit ignore
        it — block order and content never change."""
        batch = self.find_columnar(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names, target_entity_type=target_entity_type,
            value_property=value_property, default_value=default_value,
            strict=strict)
        for i in range(0, len(batch), block_size):
            yield batch.take(slice(i, i + block_size))

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=list(aggregate_event_names()),
        )
        return _apply_required(aggregate_properties(events), required)


class LEventsBackedPEvents(PEvents):
    """Default PEvents over any LEvents backend (single-host data plane)."""

    def __init__(self, levents: LEvents):
        self._l = levents

    def find(self, app_id, channel_id=None, start_time=None, until_time=None,
             entity_type=None, entity_id=None, event_names=None,
             target_entity_type=UNSET, target_entity_id=UNSET) -> List[Event]:
        return list(self._l.find(
            app_id=app_id, channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id))

    def write(self, events, app_id, channel_id=None) -> None:
        self._l.insert_batch(events, app_id, channel_id)

    def delete(self, event_ids, app_id, channel_id=None) -> None:
        for eid in event_ids:
            self._l.delete(eid, app_id, channel_id)

    def aggregate_properties(self, app_id, entity_type, channel_id=None,
                             start_time=None, until_time=None,
                             required=None) -> Dict[str, PropertyMap]:
        """Delegate to the LEvents DAO so training reads ride its
        materialized state (the base PEvents fold would replay)."""
        return self._l.aggregate_properties(
            app_id, entity_type, channel_id=channel_id,
            start_time=start_time, until_time=until_time, required=required)


# ---------------------------------------------------------------------------
# Metadata records
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class App:
    """Apps.scala record: id, name, description."""
    id: int
    name: str
    description: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class AccessKey:
    """AccessKeys.scala record: key, appid, allowed events (empty = all)."""
    key: str
    appid: int
    events: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Channel:
    """Channels.scala record; name restricted (Channels.scala:51-54)."""
    id: int
    name: str
    appid: int

    NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")

    @staticmethod
    def is_valid_name(name: str) -> bool:
        return bool(Channel.NAME_RE.match(name))


@dataclasses.dataclass(frozen=True)
class EngineInstance:
    """EngineInstances.scala:43-59 — one train run's full record."""
    id: str
    status: str  # INIT | TRAINING | COMPLETED | FAILED | INTERRUPTED
    start_time: _dt.datetime
    end_time: _dt.datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    spark_conf: Dict[str, str] = dataclasses.field(default_factory=dict)
    data_source_params: str = "{}"
    preparator_params: str = "{}"
    algorithms_params: str = "[]"
    serving_params: str = "{}"


@dataclasses.dataclass(frozen=True)
class EvaluationInstance:
    """EvaluationInstances.scala record."""
    id: str
    status: str  # INIT | EVALUATING | EVALCOMPLETED
    start_time: _dt.datetime
    end_time: _dt.datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclasses.dataclass(frozen=True)
class Model:
    """Models.scala:30-49 — opaque model blob keyed by engine-instance id."""
    id: str
    models: bytes


def generate_access_key() -> str:
    """64 url-safe chars from 48 random bytes (AccessKeys.scala:65-70)."""
    return base64.urlsafe_b64encode(os.urandom(48)).decode("ascii")


class Apps(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]: ...
    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...
    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...
    @abc.abstractmethod
    def get_all(self) -> List[App]: ...
    @abc.abstractmethod
    def update(self, app: App) -> bool: ...
    @abc.abstractmethod
    def delete(self, app_id: int) -> bool: ...


class AccessKeys(abc.ABC):
    @abc.abstractmethod
    def insert(self, k: AccessKey) -> Optional[str]: ...
    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...
    @abc.abstractmethod
    def get_all(self) -> List[AccessKey]: ...
    @abc.abstractmethod
    def get_by_appid(self, appid: int) -> List[AccessKey]: ...
    @abc.abstractmethod
    def update(self, k: AccessKey) -> bool: ...
    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...


class Channels(abc.ABC):
    @abc.abstractmethod
    def insert(self, c: Channel) -> Optional[int]: ...
    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...
    @abc.abstractmethod
    def get_by_appid(self, appid: int) -> List[Channel]: ...
    @abc.abstractmethod
    def delete(self, channel_id: int) -> bool: ...


class EngineInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, i: EngineInstance) -> str: ...
    @abc.abstractmethod
    def get(self, iid: str) -> Optional[EngineInstance]: ...
    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...
    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str,
        engine_variant: str) -> Optional[EngineInstance]: ...
    @abc.abstractmethod
    def get_completed(self, engine_id: str, engine_version: str,
                      engine_variant: str) -> List[EngineInstance]: ...
    @abc.abstractmethod
    def update(self, i: EngineInstance) -> bool: ...
    @abc.abstractmethod
    def delete(self, iid: str) -> bool: ...


class EvaluationInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, i: EvaluationInstance) -> str: ...
    @abc.abstractmethod
    def get(self, iid: str) -> Optional[EvaluationInstance]: ...
    @abc.abstractmethod
    def get_all(self) -> List[EvaluationInstance]: ...
    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]: ...
    @abc.abstractmethod
    def update(self, i: EvaluationInstance) -> bool: ...
    @abc.abstractmethod
    def delete(self, iid: str) -> bool: ...


class Models(abc.ABC):
    @abc.abstractmethod
    def insert(self, m: Model) -> None: ...
    @abc.abstractmethod
    def get(self, mid: str) -> Optional[Model]: ...
    @abc.abstractmethod
    def delete(self, mid: str) -> bool: ...
