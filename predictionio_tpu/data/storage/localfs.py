"""Filesystem model blob store.

Parity target: ``data/.../storage/localfs/LocalFSModels.scala`` — model
blobs as flat files under a configured directory, keyed by engine-instance
id. This is the MODELDATA-only backend (``PIO_STORAGE_SOURCES_<N>_TYPE=
localfs``, ``..._PATH=<dir>``); binding METADATA/EVENTDATA to it fails at
registry level, as with the reference's backend capability matrix.

Blobs land in ``<dir>/pio_model_<id>`` with an atomic rename so a crashed
writer never leaves a torn model for a concurrent deploy to load.
"""

from __future__ import annotations

import hashlib
import os
import re
import tempfile
from typing import Optional

from predictionio_tpu.data.storage import base

_SAFE = re.compile(r"[^A-Za-z0-9._-]")


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Crash-safe file write: temp file in the target directory,
    fsync, then atomic rename — readers only ever see complete
    content, and the content survives a crash that outlives the page
    cache (a kill-9 never loses a rename; power loss needs the fsync).
    Shared by the model blob store below, the jsonlfs entity-props
    snapshot, the batchpredict manifest and the training checkpoints —
    every filesystem store that persists derived state a crashed
    writer must never leave torn."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_" + os.path.basename(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic on POSIX
        try:
            # directory-entry durability (the rename itself), best
            # effort — not every fs/platform lets you fsync a dir fd
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _fname(mid: str) -> str:
    """Sanitized, INJECTIVE id -> filename mapping: the readable prefix
    cannot escape the directory, and the id-hash suffix keeps distinct
    ids ('a/b' vs 'a_b') from colliding onto one file."""
    digest = hashlib.sha256(mid.encode("utf-8")).hexdigest()[:16]
    return f"pio_model_{_SAFE.sub('_', mid)[:80]}_{digest}"


class LocalFSModels(base.Models):
    def __init__(self, config: Optional[dict] = None):
        cfg = config or {}
        self._dir = cfg.get("path") or os.path.join(
            os.getcwd(), ".pio_store", "models")
        os.makedirs(self._dir, exist_ok=True)

    def insert(self, m: base.Model) -> None:
        atomic_write_bytes(os.path.join(self._dir, _fname(m.id)), m.models)

    def get(self, mid: str) -> Optional[base.Model]:
        path = os.path.join(self._dir, _fname(mid))
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return base.Model(id=mid, models=f.read())

    def delete(self, mid: str) -> bool:
        path = os.path.join(self._dir, _fname(mid))
        if not os.path.exists(path):
            return False
        os.unlink(path)
        return True
