"""SQLite storage backend — the zero-service default.

Capability parity with the reference's JDBC backend
(``data/.../storage/jdbc/`` — the only reference backend implementing every
DAO, SURVEY §2.2): events + all metadata + model blobs in one file DB.

Schema notes: one ``events`` table partitioned by (app_id, channel_id)
columns with a covering index on (app_id, channel_id, event_time) — the
sqlite analog of the reference's HBase rowkey layout
(``HBEventsUtil.scala:81-129``: hashed entity prefix ++ event time ++ uuid).

``entity_props`` materializes the ``$set/$unset/$delete`` fold per
(app, channel, entity_type, entity_id) so the unbounded
``aggregate_properties`` — every template's training read — is one
indexed SELECT over current entities instead of an O(event history)
replay. A scope (app, channel, entity_type) becomes materialized lazily
on its first unbounded read (one backfill replay, recorded in
``entity_props_scope``); from then on every insert folds write-through
in the same transaction. Out-of-order arrivals, event-id upserts and
deletes re-derive only the touched entity; ``delete_until``/``remove``
drop the scope rows so the next read backfills fresh.
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import json
import sqlite3
import threading
import weakref
from typing import Any, Dict, Iterable, List, Optional, Sequence

import dataclasses

from predictionio_tpu.data.aggregator import (
    AGGREGATOR_EVENT_NAMES,
    EntityState,
    fold_event,
    fold_events,
)
from predictionio_tpu.data.datamap import DataMap, PropertyMap
from predictionio_tpu.data.event import Event, new_event_id, validate_event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    UNSET, AccessKey, App, Channel, EngineInstance, EvaluationInstance, Model,
)
from predictionio_tpu.utils import metrics

_SCHEMA = """
CREATE TABLE IF NOT EXISTS events (
  event_id TEXT NOT NULL,
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL DEFAULT -1,
  event TEXT NOT NULL,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  target_entity_type TEXT,
  target_entity_id TEXT,
  properties TEXT NOT NULL,
  event_time REAL NOT NULL,
  tags TEXT NOT NULL,
  pr_id TEXT,
  creation_time REAL NOT NULL,
  PRIMARY KEY (app_id, channel_id, event_id)
);
CREATE INDEX IF NOT EXISTS idx_events_scan
  ON events (app_id, channel_id, event_time);
CREATE INDEX IF NOT EXISTS idx_events_entity
  ON events (app_id, channel_id, entity_type, entity_id, event_time);
CREATE TABLE IF NOT EXISTS entity_props (
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL DEFAULT -1,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  props TEXT,
  first_updated REAL,
  last_updated REAL,
  PRIMARY KEY (app_id, channel_id, entity_type, entity_id)
);
CREATE TABLE IF NOT EXISTS entity_props_scope (
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL DEFAULT -1,
  entity_type TEXT NOT NULL,
  PRIMARY KEY (app_id, channel_id, entity_type)
);
CREATE TABLE IF NOT EXISTS apps (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL UNIQUE,
  description TEXT
);
CREATE TABLE IF NOT EXISTS access_keys (
  key TEXT PRIMARY KEY,
  appid INTEGER NOT NULL,
  events TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS channels (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL,
  appid INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS engine_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time REAL NOT NULL,
  end_time REAL NOT NULL,
  engine_id TEXT NOT NULL,
  engine_version TEXT NOT NULL,
  engine_variant TEXT NOT NULL,
  engine_factory TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  spark_conf TEXT NOT NULL DEFAULT '{}',
  data_source_params TEXT NOT NULL DEFAULT '{}',
  preparator_params TEXT NOT NULL DEFAULT '{}',
  algorithms_params TEXT NOT NULL DEFAULT '[]',
  serving_params TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS evaluation_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time REAL NOT NULL,
  end_time REAL NOT NULL,
  evaluation_class TEXT NOT NULL DEFAULT '',
  engine_params_generator_class TEXT NOT NULL DEFAULT '',
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  evaluator_results TEXT NOT NULL DEFAULT '',
  evaluator_results_html TEXT NOT NULL DEFAULT '',
  evaluator_results_json TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS models (
  id TEXT PRIMARY KEY,
  models BLOB NOT NULL
);
"""


class SqliteClient:
    """Shared connection manager; one client per DB path per process.

    File-backed paths get thread-local connections (WAL mode; sqlite file
    locking isolates their transactions). ``:memory:`` uses ONE connection
    shared by all threads (check_same_thread=False; sqlite's serialized mode
    makes that safe) — per-thread connections would each see a separate empty
    database. Because a shared connection also shares one transaction, every
    write goes through :meth:`tx`, which serializes execute+commit under a
    client lock. DAO-level ``close()`` is a no-op — ``shutdown()`` (or
    ``shutdown_all()``) tears down every connection and evicts the client.
    """

    _clients: Dict[str, "SqliteClient"] = {}
    _clients_lock = threading.Lock()

    def __init__(self, path: str):
        self.path = path
        self._in_memory = path == ":memory:"
        self._local = threading.local()
        # Per-thread connections keyed by thread ident with a weakref to the
        # owning Thread: a dying thread must not pin its connection open, so
        # conn() prunes-and-closes entries whose thread is gone.
        self._thread_conns: Dict[int, tuple] = {}
        self._conns_lock = threading.Lock()
        self._tx_lock = threading.RLock()
        self._closed = False
        self._refs = 0
        self._shared_conn: Optional[sqlite3.Connection] = None
        if self._in_memory:
            self._shared_conn = sqlite3.connect(
                ":memory:", timeout=30.0, check_same_thread=False)
        conn = self.conn()
        conn.executescript(_SCHEMA)
        conn.commit()

    @classmethod
    def shared(cls, path: str) -> "SqliteClient":
        """Obtain the client for ``path``, taking one reference. Each caller
        (one per DAO) must balance with ``release()``; the client tears down
        only when the last reference is gone."""
        with cls._clients_lock:
            client = cls._clients.get(path)
            if client is None or client._closed:
                client = cls(path)
                cls._clients[path] = client
            client._refs += 1
            return client

    @classmethod
    def shutdown_all(cls) -> None:
        """Force-teardown every client regardless of refcounts (tests)."""
        with cls._clients_lock:
            clients = list(cls._clients.values())
            cls._clients.clear()
        for c in clients:
            c._teardown()

    def release(self) -> None:
        """Drop one DAO's reference; teardown when the last one is released.
        Extra releases past zero are ignored (double-shutdown safety)."""
        with SqliteClient._clients_lock:
            if self._refs <= 0:
                return
            self._refs -= 1
            if self._refs > 0:
                return
            if SqliteClient._clients.get(self.path) is self:
                del SqliteClient._clients[self.path]
        self._teardown()

    def conn(self) -> sqlite3.Connection:
        if self._closed:
            raise base.StorageError(f"SqliteClient({self.path}) is shut down")
        if self._shared_conn is not None:
            return self._shared_conn
        c = getattr(self._local, "conn", None)
        if c is None:
            c = sqlite3.connect(self.path, timeout=30.0,
                                check_same_thread=False)
            c.execute("PRAGMA journal_mode=WAL")
            c.execute("PRAGMA synchronous=NORMAL")
            thread = threading.current_thread()
            with self._conns_lock:
                # Re-check under the lock: a concurrent _teardown() must not
                # leave a fresh connection registered on a dead client.
                if self._closed:
                    c.close()
                    raise base.StorageError(
                        f"SqliteClient({self.path}) is shut down")
                self._prune_dead_locked()
                self._thread_conns[thread.ident] = (weakref.ref(thread), c)
            self._local.conn = c
        return c

    def _prune_dead_locked(self) -> None:
        def gone(tref):
            t = tref()
            return t is None or not t.is_alive()

        dead = [ident for ident, (tref, _) in self._thread_conns.items()
                if gone(tref)]
        for ident in dead:
            _, conn = self._thread_conns.pop(ident)
            try:
                conn.close()
            except sqlite3.Error:  # pragma: no cover - best-effort cleanup
                pass

    @contextlib.contextmanager
    def tx(self):
        """One atomic write transaction: execute under the client lock,
        commit on success, roll back on error."""
        with self._tx_lock:
            conn = self.conn()
            try:
                yield conn
                conn.commit()
            except BaseException:
                conn.rollback()
                raise

    def query(self, sql: str, args: Sequence[Any] = ()) -> List[tuple]:
        """Read query returning all rows. On the shared :memory: connection
        this holds the tx lock so readers never observe another thread's
        uncommitted writes (file-backed threads have their own connections
        and WAL snapshot isolation instead)."""
        if self._shared_conn is not None:
            with self._tx_lock:
                return self._shared_conn.execute(sql, tuple(args)).fetchall()
        return self.conn().execute(sql, tuple(args)).fetchall()

    def query_iter(self, sql: str, args: Sequence[Any] = ()):
        """Streaming read with snapshot semantics for large scans.

        File-backed: a FRESH read connection per scan, so the WAL snapshot
        isolates it from writes the caller makes through its own connection
        while iterating (same-connection write-while-step visibility is
        undefined in sqlite). Shared ``:memory:``: no second connection can
        see the data, so materialize under the tx lock instead.
        """
        if self._shared_conn is not None:
            with self._tx_lock:
                rows = self._shared_conn.execute(sql, tuple(args)).fetchall()
            yield from rows
            return
        if self._closed:
            raise base.StorageError(f"SqliteClient({self.path}) is shut down")
        conn = sqlite3.connect(self.path, timeout=30.0)
        try:
            yield from conn.execute(sql, tuple(args))
        finally:
            conn.close()

    def query_one(self, sql: str, args: Sequence[Any] = ()) -> Optional[tuple]:
        rows = self.query(sql, args)
        return rows[0] if rows else None

    def shutdown(self) -> None:
        """Close every connection and evict this client from the cache."""
        with SqliteClient._clients_lock:
            if SqliteClient._clients.get(self.path) is self:
                del SqliteClient._clients[self.path]
        self._teardown()

    def _teardown(self) -> None:
        with self._conns_lock:
            self._closed = True
            conns = [c for _, c in self._thread_conns.values()]
            self._thread_conns.clear()
        if self._shared_conn is not None:
            conns.append(self._shared_conn)
            self._shared_conn = None
        for c in conns:
            try:
                c.close()
            except sqlite3.Error:  # pragma: no cover - best-effort cleanup
                pass

    def close(self) -> None:
        """DAO-level close: a no-op (other DAOs share this client).

        Use :meth:`shutdown` for an explicit client-level teardown.
        """


def _ts(t: _dt.datetime) -> float:
    return t.timestamp()


def _from_ts(x: float) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(x, tz=_dt.timezone.utc)


def _row_to_event(row) -> Event:
    (event_id, event, entity_type, entity_id, tet, tei, props, etime, tags,
     pr_id, ctime) = row
    return Event(
        event=event, entity_type=entity_type, entity_id=entity_id,
        target_entity_type=tet, target_entity_id=tei,
        properties=DataMap(json.loads(props)),
        event_time=_from_ts(etime), tags=tuple(json.loads(tags)),
        pr_id=pr_id, creation_time=_from_ts(ctime), event_id=event_id,
    )


_EVENT_COLS = ("event_id, event, entity_type, entity_id, target_entity_type, "
               "target_entity_id, properties, event_time, tags, pr_id, "
               "creation_time")


class SqliteLEvents(base.LEvents):
    metrics_backend = "sqlite"
    # INSERT OR REPLACE keyed by (app, channel, event_id): retried
    # inserts with pre-assigned ids replay to the identical state
    idempotent_event_writes = True
    # entity_id-filtered finds are index lookups, not table scans —
    # readers (the fold-in gather) may issue many small per-entity
    # reads instead of one shared scan
    indexed_entity_reads = True

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self._client = SqliteClient.shared(config.get("path", ":memory:"))

    def _chan(self, channel_id) -> int:
        return -1 if channel_id is None else int(channel_id)

    def init(self, app_id, channel_id=None) -> bool:
        return True  # single-table layout; nothing per-app to create

    def remove(self, app_id, channel_id=None) -> bool:
        with self._client.tx() as c:
            c.execute("DELETE FROM events WHERE app_id=? AND channel_id=?",
                      (int(app_id), self._chan(channel_id)))
            self._drop_materialized(c, int(app_id), self._chan(channel_id))
        return True

    # -- materialized entity-property state -------------------------------
    # All helpers run on the transaction connection ``c`` so fold
    # maintenance commits (or rolls back) atomically with the event write.

    @staticmethod
    def _materialized_scopes(c, aid: int, chan: int) -> set:
        return {r[0] for r in c.execute(
            "SELECT entity_type FROM entity_props_scope"
            " WHERE app_id=? AND channel_id=?", (aid, chan))}

    @staticmethod
    def _drop_materialized(c, aid: int, chan: int) -> None:
        cur = c.execute(
            "DELETE FROM entity_props_scope WHERE app_id=? AND channel_id=?",
            (aid, chan))
        c.execute("DELETE FROM entity_props WHERE app_id=? AND channel_id=?",
                  (aid, chan))
        if cur.rowcount:
            metrics.AGGREGATE_SCOPE_DROPS.inc(amount=cur.rowcount,
                                              backend="sqlite")

    @staticmethod
    def _load_state(c, aid: int, chan: int, etype: str,
                    eid: str) -> Optional[EntityState]:
        row = c.execute(
            "SELECT props, first_updated, last_updated FROM entity_props"
            " WHERE app_id=? AND channel_id=? AND entity_type=?"
            " AND entity_id=?", (aid, chan, etype, eid)).fetchone()
        if row is None:
            return None
        return EntityState.from_record(
            [None if row[0] is None else json.loads(row[0]), row[1], row[2]])

    @staticmethod
    def _write_state(c, aid: int, chan: int, etype: str, eid: str,
                     st: Optional[EntityState]) -> None:
        if st is None:
            c.execute(
                "DELETE FROM entity_props WHERE app_id=? AND channel_id=?"
                " AND entity_type=? AND entity_id=?", (aid, chan, etype, eid))
            return
        rec = st.to_record()
        c.execute(
            "INSERT OR REPLACE INTO entity_props (app_id, channel_id,"
            " entity_type, entity_id, props, first_updated, last_updated)"
            " VALUES (?,?,?,?,?,?,?)",
            (aid, chan, etype, eid,
             None if rec[0] is None else json.dumps(rec[0], sort_keys=True),
             rec[1], rec[2]))

    def _entity_events(self, c, aid: int, chan: int, etype: str,
                       eid: str) -> List[Event]:
        """One entity's special events in replay order (event_time, with
        rowid breaking ties the same way the index scan does)."""
        names = ",".join("?" * len(AGGREGATOR_EVENT_NAMES))
        rows = c.execute(
            f"SELECT event, properties, event_time FROM events"
            f" WHERE app_id=? AND channel_id=? AND entity_type=?"
            f" AND entity_id=? AND event IN ({names})"
            f" ORDER BY event_time, rowid",
            (aid, chan, etype, eid) + AGGREGATOR_EVENT_NAMES).fetchall()
        return [Event(event=name, entity_type=etype, entity_id=eid,
                      properties=DataMap(json.loads(props)),
                      event_time=_from_ts(etime))
                for name, props, etime in rows]

    def _refold_entity(self, c, aid: int, chan: int, etype: str,
                       eid: str) -> None:
        """Re-derive ONE entity's state from its (indexed, small) event
        history — the out-of-order / upsert / delete repair path."""
        st = None
        for e in self._entity_events(c, aid, chan, etype, eid):
            st = fold_event(st, e)
        self._write_state(c, aid, chan, etype, eid, st)

    def _fold_through(self, c, aid: int, chan: int, events: List[Event],
                      refold: Optional[set] = None) -> None:
        """Write-through fold of freshly inserted events (already in the
        ``events`` table on this transaction). Only scopes a reader has
        materialized pay anything; entities in ``refold`` (replaced
        event ids, out-of-order arrivals) re-derive from history, the
        rest fold incrementally."""
        special = [e for e in events if e.event in AGGREGATOR_EVENT_NAMES]
        if not special and not refold:
            return
        scopes = self._materialized_scopes(c, aid, chan)
        if not scopes:
            return
        refold = {k for k in (refold or set()) if k[0] in scopes}
        by_entity: Dict[tuple, List[Event]] = {}
        for e in special:
            if e.entity_type in scopes:
                by_entity.setdefault((e.entity_type, e.entity_id),
                                     []).append(e)
        for key, evs in by_entity.items():
            if key in refold:
                continue
            st = self._load_state(c, aid, chan, *key)
            if st is not None and st.last_updated is not None and \
                    min(e.event_time for e in evs) < st.last_updated:
                # out-of-order arrival: the replay would sort this before
                # already-folded events — re-derive from history
                refold.add(key)
                continue
            self._write_state(c, aid, chan, *key, fold_events(evs, st))
        for key in refold:
            self._refold_entity(c, aid, chan, *key)

    def _collision_refolds(self, c, aid: int, chan: int,
                           events: List[Event]) -> set:
        """Entities whose fold is invalidated by event-id upserts: the
        replaced row's contribution disappears, so both the old and the
        new row's entity must re-derive. Only pre-set event ids can
        collide (generated ids are fresh UUIDs)."""
        preset = [e for e in events if e.event_id]
        refold: set = set()
        # duplicates WITHIN the batch: only the last row survives the
        # INSERT OR REPLACE, so every duplicated event's entity must
        # re-derive from the table instead of being folded incrementally
        seen: Dict[str, Event] = {}
        for e in preset:
            prev = seen.get(e.event_id)
            if prev is not None:
                for dup in (prev, e):
                    if dup.event in AGGREGATOR_EVENT_NAMES:
                        refold.add((dup.entity_type, dup.entity_id))
            seen[e.event_id] = e
        for i in range(0, len(preset), 500):
            chunk = preset[i:i + 500]
            marks = ",".join("?" * len(chunk))
            hits = {r[0]: (r[1], r[2], r[3]) for r in c.execute(
                f"SELECT event_id, event, entity_type, entity_id FROM events"
                f" WHERE app_id=? AND channel_id=? AND event_id IN ({marks})",
                (aid, chan) + tuple(e.event_id for e in chunk))}
            for e in chunk:
                hit = hits.get(e.event_id)
                if hit is None:
                    continue
                old_event, old_etype, old_eid = hit
                if old_event in AGGREGATOR_EVENT_NAMES:
                    refold.add((old_etype, old_eid))
                if e.event in AGGREGATOR_EVENT_NAMES:
                    refold.add((e.entity_type, e.entity_id))
        return refold

    def materialized_aggregate(self, app_id, entity_type, channel_id=None
                               ) -> Optional[Dict[str, PropertyMap]]:
        aid, chan = int(app_id), self._chan(channel_id)
        try:
            # scope check, (one-time) backfill and the state read all run
            # under ONE tx: a concurrent delete_until/remove dropping the
            # scope can never interleave between the check and the read
            # (it would hand back an empty table for a non-empty store)
            with self._client.tx() as c:
                if c.execute(
                        "SELECT 1 FROM entity_props_scope WHERE app_id=?"
                        " AND channel_id=? AND entity_type=?",
                        (aid, chan, entity_type)).fetchone() is None:
                    # backfill ONCE: replay the scope's history into
                    # entity_props (tombstones too) and record the scope.
                    # The scope row goes in BEFORE scanning: the write
                    # upgrades this tx to a real write transaction, so a
                    # concurrent sqlite writer (another process; threads
                    # already serialize on the tx lock) blocks until the
                    # backfill commits instead of inserting an event the
                    # scan missed and the scope-row check skipped
                    c.execute(
                        "INSERT OR REPLACE INTO entity_props_scope"
                        " (app_id, channel_id, entity_type) VALUES (?,?,?)",
                        (aid, chan, entity_type))
                    metrics.AGGREGATE_BACKFILLS.inc(backend="sqlite")
                    names = ",".join("?" * len(AGGREGATOR_EVENT_NAMES))
                    rows = c.execute(
                        f"SELECT entity_id, event, properties, event_time"
                        f" FROM events WHERE app_id=? AND channel_id=?"
                        f" AND entity_type=? AND event IN ({names})"
                        f" ORDER BY event_time, rowid",
                        (aid, chan, entity_type)
                        + AGGREGATOR_EVENT_NAMES).fetchall()
                    states: Dict[str, Optional[EntityState]] = {}
                    for eid, name, props, etime in rows:
                        states[eid] = fold_event(
                            states.get(eid),
                            Event(event=name, entity_type=entity_type,
                                  entity_id=eid,
                                  properties=DataMap(json.loads(props)),
                                  event_time=_from_ts(etime)))
                    for eid, st in states.items():
                        self._write_state(c, aid, chan, entity_type, eid, st)
                state_rows = c.execute(
                    "SELECT entity_id, props, first_updated, last_updated"
                    " FROM entity_props WHERE app_id=? AND channel_id=?"
                    " AND entity_type=? AND props IS NOT NULL",
                    (aid, chan, entity_type)).fetchall()
        except sqlite3.OperationalError:
            # e.g. a read-only DB file/filesystem rejecting the backfill
            # write, or lock contention: aggregate_properties must stay
            # servable — fall back to the pure-read replay
            return None
        out: Dict[str, PropertyMap] = {}
        for eid, props, first, last in state_rows:
            out[eid] = PropertyMap(
                json.loads(props),
                first_updated=None if first is None else _from_ts(first),
                last_updated=None if last is None else _from_ts(last))
        return out

    def close(self) -> None:
        self._client.close()

    def shutdown(self) -> None:
        """Release this DAO's client reference (idempotent)."""
        if not getattr(self, "_released", False):
            self._released = True
            self._client.release()

    def insert(self, event: Event, app_id, channel_id=None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Iterable[Event], app_id,
                     channel_id=None) -> List[str]:
        """Bulk insert in one transaction (no reference analog; the TPU
        ingest path needs it for import throughput). Write-through: the
        same transaction folds the special events into any materialized
        entity_props scopes."""
        aid, chan = int(app_id), self._chan(channel_id)
        ids: List[str] = []
        rows = []
        evs: List[Event] = []
        for event in events:
            validate_event(event)
            eid = event.event_id or new_event_id()
            ids.append(eid)
            evs.append(event.with_id(eid))
            rows.append(
                (eid, aid, chan, event.event,
                 event.entity_type, event.entity_id, event.target_entity_type,
                 event.target_entity_id, event.properties.to_json(),
                 _ts(event.event_time), json.dumps(list(event.tags)),
                 event.pr_id, _ts(event.creation_time)))
        with self._client.tx() as c:
            refold = self._collision_refolds(c, aid, chan, evs)
            c.executemany(
                "INSERT OR REPLACE INTO events (event_id, app_id, channel_id,"
                " event, entity_type, entity_id, target_entity_type,"
                " target_entity_id, properties, event_time, tags, pr_id,"
                " creation_time) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)", rows)
            self._fold_through(c, aid, chan, evs, refold)
        return ids

    def insert_raw_batch(self, rows: List[tuple], app_id: int,
                         channel_id: Optional[int] = None) -> None:
        """Pre-validated columnar insert for the native import path: rows
        are (event_id, event, entity_type, entity_id, target_entity_type,
        target_entity_id, properties_json, event_time_epoch_sec,
        tags_json, pr_id, creation_time_epoch_sec) — app/channel encoding
        stays the backend's business. Callers (tools/export_import) are
        responsible for validation — this is the data-plane fast lane,
        not the API."""
        aid, chan = int(app_id), self._chan(channel_id)
        full = [(r[0], aid, chan) + r[1:] for r in rows]
        with self._client.tx() as c:
            # the fast lane skips per-event fold bookkeeping: entities of
            # special rows landing in a materialized scope re-derive from
            # the table after the bulk insert (imports usually target
            # fresh apps, where no scope is materialized and this is free)
            scopes = self._materialized_scopes(c, aid, chan)
            refold = set()
            if scopes:
                refold = {(r[2], r[3]) for r in rows
                          if r[1] in AGGREGATOR_EVENT_NAMES
                          and r[2] in scopes}
                # rows replacing an EXISTING special event (id collision)
                # erase that event's fold contribution too — its entity
                # must re-derive even if the new row is non-special
                ids = [r[0] for r in rows]
                for i in range(0, len(ids), 500):
                    chunk = ids[i:i + 500]
                    marks = ",".join("?" * len(chunk))
                    names = ",".join("?" * len(AGGREGATOR_EVENT_NAMES))
                    refold.update(
                        (r[0], r[1]) for r in c.execute(
                            f"SELECT entity_type, entity_id FROM events"
                            f" WHERE app_id=? AND channel_id=?"
                            f" AND event_id IN ({marks})"
                            f" AND event IN ({names})",
                            (aid, chan) + tuple(chunk)
                            + AGGREGATOR_EVENT_NAMES)
                        if r[0] in scopes)
            c.executemany(
                "INSERT OR REPLACE INTO events (event_id, app_id, channel_id,"
                " event, entity_type, entity_id, target_entity_type,"
                " target_entity_id, properties, event_time, tags, pr_id,"
                " creation_time) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)", full)
            for key in refold:
                self._refold_entity(c, aid, chan, *key)

    def iter_raw_rows(self, app_id: int,
                      channel_id: Optional[int] = None):
        """Data-plane raw read (inverse of ``insert_raw_batch``, same
        tuple shape): the columnar exporter streams rows without ever
        building Event objects."""
        yield from self._client.query_iter(
            "SELECT event_id, event, entity_type, entity_id,"
            " target_entity_type, target_entity_id, properties,"
            " event_time, tags, pr_id, creation_time FROM events"
            " WHERE app_id=? AND channel_id=? ORDER BY event_time, rowid",
            (int(app_id), self._chan(channel_id)))

    def get(self, event_id, app_id, channel_id=None) -> Optional[Event]:
        row = self._client.query_one(
            f"SELECT {_EVENT_COLS} FROM events WHERE app_id=? AND channel_id=?"
            " AND event_id=?",
            (int(app_id), self._chan(channel_id), event_id))
        return _row_to_event(row) if row else None

    def delete(self, event_id, app_id, channel_id=None) -> bool:
        aid, chan = int(app_id), self._chan(channel_id)
        with self._client.tx() as c:
            hit = c.execute(
                "SELECT event, entity_type, entity_id FROM events"
                " WHERE app_id=? AND channel_id=? AND event_id=?",
                (aid, chan, event_id)).fetchone()
            cur = c.execute(
                "DELETE FROM events WHERE app_id=? AND channel_id=?"
                " AND event_id=?", (aid, chan, event_id))
            if cur.rowcount > 0 and hit is not None \
                    and hit[0] in AGGREGATOR_EVENT_NAMES \
                    and hit[1] in self._materialized_scopes(c, aid, chan):
                self._refold_entity(c, aid, chan, hit[1], hit[2])
            return cur.rowcount > 0

    def delete_until(self, app_id, until_time, channel_id=None) -> int:
        """One DELETE statement instead of the per-event loop."""
        aid, chan = int(app_id), self._chan(channel_id)
        with self._client.tx() as c:
            cur = c.execute(
                "DELETE FROM events WHERE app_id=? AND channel_id=? AND "
                "event_time<?", (aid, chan, _ts(until_time)))
            if cur.rowcount:
                # bulk cutoff touches arbitrarily many entities: drop the
                # materialized scopes and let the next unbounded read
                # backfill from the surviving history
                self._drop_materialized(c, aid, chan)
            return int(cur.rowcount)

    def find(self, app_id, channel_id=None, start_time=None, until_time=None,
             entity_type=None, entity_id=None, event_names=None,
             target_entity_type=UNSET, target_entity_id=UNSET,
             limit=None, reversed=False) -> Iterable[Event]:
        where = ["app_id=?", "channel_id=?"]
        args: List[Any] = [int(app_id), self._chan(channel_id)]
        if start_time is not None:
            where.append("event_time>=?")
            args.append(_ts(start_time))
        if until_time is not None:
            where.append("event_time<?")
            args.append(_ts(until_time))
        if entity_type is not None:
            where.append("entity_type=?")
            args.append(entity_type)
        if entity_id is not None:
            where.append("entity_id=?")
            args.append(entity_id)
        if event_names is not None:
            names = list(event_names)
            where.append(f"event IN ({','.join('?' * len(names))})")
            args.extend(names)
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                where.append("target_entity_type IS NULL")
            else:
                where.append("target_entity_type=?")
                args.append(target_entity_type)
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                where.append("target_entity_id IS NULL")
            else:
                where.append("target_entity_id=?")
                args.append(target_entity_id)
        order = "DESC" if reversed else "ASC"
        sql = (f"SELECT {_EVENT_COLS} FROM events WHERE {' AND '.join(where)} "
               f"ORDER BY event_time {order}")
        if limit is not None and limit >= 0:
            sql += f" LIMIT {int(limit)}"
        # query_iter gives snapshot semantics (fresh WAL read connection
        # for files; materialized under lock for shared :memory:) so
        # callers may write while iterating.
        for row in self._client.query_iter(sql, args):
            yield _row_to_event(row)

    # -- tail reads (find_since contract, base.py) -------------------------
    # Arrival order = rowid order (INSERT OR REPLACE re-inserts, so an
    # id-keyed upsert re-surfaces to tail consumers — re-delivery of the
    # newest version, never a miss).

    def find_since(self, app_id, channel_id=None, cursor=None, limit=None):
        aid, chan = int(app_id), self._chan(channel_id)
        after = int(cursor.get("rowid", -1)) if cursor else -1
        last_eid = cursor.get("eventId") if cursor else None
        if after >= 0:
            # the cursor is self-validating: the row it points at must
            # still exist AND still hold the event it held when the
            # cursor was minted. A bulk delete followed by re-ingest
            # RECYCLES rowids (sqlite hands out max+1, so trimming the
            # tail re-issues the trimmed range) — a bare rowid compare
            # against MAX(rowid) cannot see that, and would silently
            # skip every event re-landed at a recycled rowid <= cursor.
            row = self._client.query_one(
                "SELECT event_id FROM events WHERE app_id=? AND"
                " channel_id=? AND rowid=?", (aid, chan, after))
            if row is None or (last_eid is not None
                               and row[0] != last_eid):
                after = -1
                last_eid = None
        sql = (f"SELECT {_EVENT_COLS}, rowid FROM events WHERE app_id=?"
               f" AND channel_id=? AND rowid>? ORDER BY rowid ASC")
        args: List[Any] = [aid, chan, after]
        if limit is not None and int(limit) >= 0:
            sql += f" LIMIT {int(limit)}"
        events: List[Event] = []
        last = after
        for row in self._client.query_iter(sql, args):
            events.append(_row_to_event(row[:-1]))
            last = int(row[-1])
        if events:
            last_eid = events[-1].event_id
        cur = {"kind": "sqlite", "rowid": last}
        if last >= 0 and last_eid is not None:
            cur["eventId"] = last_eid
        return events, cur

    def tail_cursor(self, app_id, channel_id=None):
        row = self._client.query_one(
            "SELECT rowid, event_id FROM events WHERE app_id=? AND"
            " channel_id=? ORDER BY rowid DESC LIMIT 1",
            (int(app_id), self._chan(channel_id)))
        if row is None:
            return {"kind": "sqlite", "rowid": -1}
        return {"kind": "sqlite", "rowid": int(row[0]),
                "eventId": row[1]}

    def tail_watermark(self, app_id, channel_id=None):
        row = self._client.query_one(
            "SELECT event_id, event_time, rowid FROM events WHERE app_id=?"
            " AND channel_id=? ORDER BY rowid DESC LIMIT 1",
            (int(app_id), self._chan(channel_id)))
        if row is None:
            return {"cursor": {"kind": "sqlite", "rowid": -1},
                    "lastEventId": None, "lastEventTime": None}
        return {"cursor": {"kind": "sqlite", "rowid": int(row[2]),
                           "eventId": row[0]},
                "lastEventId": row[0],
                "lastEventTime": _from_ts(row[1]).isoformat()}


class SqlitePEvents(base.LEventsBackedPEvents):
    def __init__(self, config: Optional[dict] = None):
        super().__init__(SqliteLEvents(config))

    def shutdown(self) -> None:
        self._l.shutdown()

    def find_columnar(self, app_id, channel_id=None, start_time=None,
                      until_time=None, entity_type=None, event_names=None,
                      target_entity_type=UNSET, value_property=None,
                      default_value=1.0, strict=True):
        """Native columnar scan: the value column is extracted inside SQL
        (``json_extract``) so no per-row Python Event/DataMap objects are
        built — the TPU ingest fast path (SURVEY hard part #2)."""
        import numpy as np

        from predictionio_tpu.data.columnar import ColumnarEvents

        if value_property is not None and '"' in value_property:
            # sqlite JSON paths cannot escape double quotes in key names;
            # fall back to the generic (oracle) path for exotic names
            return super().find_columnar(
                app_id, channel_id=channel_id, start_time=start_time,
                until_time=until_time, entity_type=entity_type,
                event_names=event_names,
                target_entity_type=target_entity_type,
                value_property=value_property, default_value=default_value,
                strict=strict)

        sql, args = self._columnar_sql(
            app_id, channel_id, start_time, until_time, entity_type,
            event_names, target_entity_type, value_property,
            order="event_time ASC")
        rows = list(self._l._client.query_iter(sql, args))
        return self._columnar_rows(rows, value_property, default_value,
                                   strict)

    def find_columnar_blocks(self, app_id, channel_id=None, start_time=None,
                             until_time=None, entity_type=None,
                             event_names=None, target_entity_type=UNSET,
                             value_property=None, default_value=1.0,
                             strict=True, block_size=1_000_000,
                             prefetch=0):
        """Streaming scan via rowid keyset pagination — fixed-size
        columnar blocks in storage (rowid) order, never materializing the
        whole result set (the JDBCPEvents.scala:31-100 partitioned-read
        analog). Falls back to the generic sliced scan for exotic
        property names (same reason as find_columnar). ``prefetch`` is
        accepted but ignored: one connection, one cursor — there is no
        decode stage to run ahead."""
        del prefetch
        if value_property is not None and '"' in value_property:
            yield from super().find_columnar_blocks(
                app_id, channel_id=channel_id, start_time=start_time,
                until_time=until_time, entity_type=entity_type,
                event_names=event_names,
                target_entity_type=target_entity_type,
                value_property=value_property, default_value=default_value,
                strict=strict, block_size=block_size)
            return
        last_rowid = -1
        while True:
            sql, args = self._columnar_sql(
                app_id, channel_id, start_time, until_time, entity_type,
                event_names, target_entity_type, value_property,
                order="rowid ASC", rowid_after=last_rowid,
                limit=int(block_size), with_rowid=True)
            rows = list(self._l._client.query_iter(sql, args))
            if not rows:
                return
            last_rowid = int(rows[-1][-1])
            yield self._columnar_rows([r[:-1] for r in rows],
                                      value_property, default_value, strict)
            if len(rows) < block_size:
                return

    def _columnar_sql(self, app_id, channel_id, start_time, until_time,
                      entity_type, event_names, target_entity_type,
                      value_property, *, order: str,
                      rowid_after: Optional[int] = None,
                      limit: Optional[int] = None,
                      with_rowid: bool = False):
        lev = self._l
        where = ["app_id=?", "channel_id=?"]
        args: List[Any] = [int(app_id), lev._chan(channel_id)]
        if rowid_after is not None:
            where.append("rowid>?")
            args.append(int(rowid_after))
        if start_time is not None:
            where.append("event_time>=?")
            args.append(_ts(start_time))
        if until_time is not None:
            where.append("event_time<?")
            args.append(_ts(until_time))
        if entity_type is not None:
            where.append("entity_type=?")
            args.append(entity_type)
        if event_names is not None:
            names = list(event_names)
            where.append(f"event IN ({','.join('?' * len(names))})")
            args.extend(names)
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                where.append("target_entity_type IS NULL")
            else:
                where.append("target_entity_type=?")
                args.append(target_entity_type)
        if value_property is not None:
            # json_type distinguishes numbers from booleans (both extract
            # as ints) and from missing/null keys; the type column drives
            # the strict-mode check in _columnar_rows
            prop_path = '$."' + value_property + '"'
            value_col = ("json_extract(properties, ?), "
                         "json_type(properties, ?)")
            # SELECT-list params bind before the WHERE params
            args = [prop_path, prop_path] + args
        else:
            value_col = "NULL, NULL"
        rowid_col = ", rowid" if with_rowid else ""
        sql = (f"SELECT entity_id, target_entity_id, {value_col}, event_time,"
               f" event{rowid_col} FROM events"
               f" WHERE {' AND '.join(where)} ORDER BY {order}")
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        return sql, args

    def _columnar_rows(self, rows, value_property, default_value, strict):
        import numpy as np

        from predictionio_tpu.data.columnar import ColumnarEvents

        n = len(rows)
        ents = np.empty(n, dtype=object)
        tgts = np.empty(n, dtype=object)
        vals = np.full(n, float(default_value), dtype=np.float32)
        times = np.empty(n, dtype=np.float64)
        names_out = np.empty(n, dtype=object)
        for i, (ent, tgt, val, jtype, etime, name) in enumerate(rows):
            ents[i] = ent
            tgts[i] = tgt
            if jtype in ("integer", "real"):
                vals[i] = val
            elif strict and jtype not in (None, "null"):
                raise ValueError(
                    f"property {value_property!r} of event for entity "
                    f"{ent!r} is non-numeric (JSON {jtype})")
            times[i] = etime
            names_out[i] = name
        return ColumnarEvents(ents, tgts, vals, times, names_out)


class _SqliteMetaDAO:
    """Shared client plumbing for the metadata/model DAOs."""

    def __init__(self, config: Optional[dict] = None):
        self._c = SqliteClient.shared((config or {}).get("path", ":memory:"))

    def close(self) -> None:
        self._c.close()

    def shutdown(self) -> None:
        """Release this DAO's client reference (idempotent)."""
        if not getattr(self, "_released", False):
            self._released = True
            self._c.release()


class SqliteApps(_SqliteMetaDAO, base.Apps):

    def insert(self, app: App) -> Optional[int]:
        try:
            with self._c.tx() as c:
                if app.id:
                    cur = c.execute(
                        "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                        (app.id, app.name, app.description))
                else:
                    cur = c.execute(
                        "INSERT INTO apps (name, description) VALUES (?,?)",
                        (app.name, app.description))
                return cur.lastrowid if not app.id else app.id
        except sqlite3.IntegrityError:
            return None

    def get(self, app_id):
        row = self._c.query_one(
            "SELECT id, name, description FROM apps WHERE id=?",
            (int(app_id),))
        return App(*row) if row else None

    def get_by_name(self, name):
        row = self._c.query_one(
            "SELECT id, name, description FROM apps WHERE name=?", (name,))
        return App(*row) if row else None

    def get_all(self):
        return [App(*r) for r in self._c.query(
            "SELECT id, name, description FROM apps ORDER BY id")]

    def update(self, app: App) -> bool:
        with self._c.tx() as c:
            cur = c.execute("UPDATE apps SET name=?, description=? WHERE id=?",
                            (app.name, app.description, app.id))
            return cur.rowcount > 0

    def delete(self, app_id) -> bool:
        with self._c.tx() as c:
            cur = c.execute("DELETE FROM apps WHERE id=?", (int(app_id),))
            return cur.rowcount > 0


class SqliteAccessKeys(_SqliteMetaDAO, base.AccessKeys):

    def insert(self, k: AccessKey) -> Optional[str]:
        key = k.key or base.generate_access_key()
        with self._c.tx() as c:
            c.execute("INSERT OR REPLACE INTO access_keys (key, appid, events)"
                      " VALUES (?,?,?)",
                      (key, k.appid, json.dumps(list(k.events))))
        return key

    def get(self, key):
        row = self._c.query_one(
            "SELECT key, appid, events FROM access_keys WHERE key=?", (key,))
        return AccessKey(row[0], row[1], tuple(json.loads(row[2]))) if row else None

    def get_all(self):
        return [AccessKey(r[0], r[1], tuple(json.loads(r[2])))
                for r in self._c.query(
                    "SELECT key, appid, events FROM access_keys")]

    def get_by_appid(self, appid):
        return [AccessKey(r[0], r[1], tuple(json.loads(r[2])))
                for r in self._c.query(
                    "SELECT key, appid, events FROM access_keys WHERE appid=?",
                    (int(appid),))]

    def update(self, k: AccessKey) -> bool:
        with self._c.tx() as c:
            cur = c.execute(
                "UPDATE access_keys SET appid=?, events=? WHERE key=?",
                (k.appid, json.dumps(list(k.events)), k.key))
            return cur.rowcount > 0

    def delete(self, key) -> bool:
        with self._c.tx() as c:
            cur = c.execute("DELETE FROM access_keys WHERE key=?", (key,))
            return cur.rowcount > 0


class SqliteChannels(_SqliteMetaDAO, base.Channels):

    def insert(self, c: Channel) -> Optional[int]:
        if not Channel.is_valid_name(c.name):
            return None
        try:
            with self._c.tx() as conn:
                if c.id:
                    cur = conn.execute(
                        "INSERT INTO channels (id, name, appid) VALUES (?,?,?)",
                        (c.id, c.name, c.appid))
                else:
                    cur = conn.execute(
                        "INSERT INTO channels (name, appid) VALUES (?,?)",
                        (c.name, c.appid))
                return c.id if c.id else cur.lastrowid
        except sqlite3.IntegrityError:
            return None

    def get(self, channel_id):
        row = self._c.query_one(
            "SELECT id, name, appid FROM channels WHERE id=?",
            (int(channel_id),))
        return Channel(*row) if row else None

    def get_by_appid(self, appid):
        return [Channel(*r) for r in self._c.query(
            "SELECT id, name, appid FROM channels WHERE appid=?",
            (int(appid),))]

    def delete(self, channel_id) -> bool:
        with self._c.tx() as c:
            cur = c.execute("DELETE FROM channels WHERE id=?",
                            (int(channel_id),))
            return cur.rowcount > 0


_EI_COLS = ("id, status, start_time, end_time, engine_id, engine_version,"
            " engine_variant, engine_factory, batch, env, spark_conf,"
            " data_source_params, preparator_params, algorithms_params,"
            " serving_params")


def _row_to_ei(r) -> EngineInstance:
    return EngineInstance(
        id=r[0], status=r[1], start_time=_from_ts(r[2]), end_time=_from_ts(r[3]),
        engine_id=r[4], engine_version=r[5], engine_variant=r[6],
        engine_factory=r[7], batch=r[8], env=json.loads(r[9]),
        spark_conf=json.loads(r[10]), data_source_params=r[11],
        preparator_params=r[12], algorithms_params=r[13], serving_params=r[14])


class SqliteEngineInstances(_SqliteMetaDAO, base.EngineInstances):

    def insert(self, i: EngineInstance) -> str:
        iid = i.id or new_ei_id()
        i = dataclasses.replace(i, id=iid)
        with self._c.tx() as c:
            c.execute(
                f"INSERT OR REPLACE INTO engine_instances ({_EI_COLS})"
                " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (i.id, i.status, _ts(i.start_time), _ts(i.end_time),
                 i.engine_id, i.engine_version, i.engine_variant,
                 i.engine_factory, i.batch, json.dumps(i.env),
                 json.dumps(i.spark_conf), i.data_source_params,
                 i.preparator_params, i.algorithms_params, i.serving_params))
        return iid

    def get(self, iid):
        row = self._c.query_one(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE id=?", (iid,))
        return _row_to_ei(row) if row else None

    def get_all(self):
        return [_row_to_ei(r) for r in self._c.query(
            f"SELECT {_EI_COLS} FROM engine_instances")]

    def get_completed(self, engine_id, engine_version, engine_variant):
        return [_row_to_ei(r) for r in self._c.query(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE status='COMPLETED'"
            " AND engine_id=? AND engine_version=? AND engine_variant=?"
            " ORDER BY start_time DESC",
            (engine_id, engine_version, engine_variant))]

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        rows = self.get_completed(engine_id, engine_version, engine_variant)
        return rows[0] if rows else None

    def update(self, i: EngineInstance) -> bool:
        with self._c.tx() as c:
            cur = c.execute(
                "UPDATE engine_instances SET status=?, start_time=?,"
                " end_time=?, engine_id=?, engine_version=?, engine_variant=?,"
                " engine_factory=?, batch=?, env=?, spark_conf=?,"
                " data_source_params=?, preparator_params=?,"
                " algorithms_params=?, serving_params=? WHERE id=?",
                (i.status, _ts(i.start_time), _ts(i.end_time), i.engine_id,
                 i.engine_version, i.engine_variant, i.engine_factory, i.batch,
                 json.dumps(i.env), json.dumps(i.spark_conf),
                 i.data_source_params, i.preparator_params,
                 i.algorithms_params, i.serving_params, i.id))
            return cur.rowcount > 0

    def delete(self, iid) -> bool:
        with self._c.tx() as c:
            cur = c.execute("DELETE FROM engine_instances WHERE id=?", (iid,))
            return cur.rowcount > 0


_EVI_COLS = ("id, status, start_time, end_time, evaluation_class,"
             " engine_params_generator_class, batch, env, evaluator_results,"
             " evaluator_results_html, evaluator_results_json")


def _row_to_evi(r) -> EvaluationInstance:
    return EvaluationInstance(
        id=r[0], status=r[1], start_time=_from_ts(r[2]), end_time=_from_ts(r[3]),
        evaluation_class=r[4], engine_params_generator_class=r[5], batch=r[6],
        env=json.loads(r[7]), evaluator_results=r[8],
        evaluator_results_html=r[9], evaluator_results_json=r[10])


class SqliteEvaluationInstances(_SqliteMetaDAO, base.EvaluationInstances):

    def insert(self, i: EvaluationInstance) -> str:
        iid = i.id or new_ei_id("evi")
        i = dataclasses.replace(i, id=iid)
        with self._c.tx() as c:
            c.execute(
                f"INSERT OR REPLACE INTO evaluation_instances ({_EVI_COLS})"
                " VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                (i.id, i.status, _ts(i.start_time), _ts(i.end_time),
                 i.evaluation_class, i.engine_params_generator_class, i.batch,
                 json.dumps(i.env), i.evaluator_results,
                 i.evaluator_results_html, i.evaluator_results_json))
        return iid

    def get(self, iid):
        row = self._c.query_one(
            f"SELECT {_EVI_COLS} FROM evaluation_instances WHERE id=?", (iid,))
        return _row_to_evi(row) if row else None

    def get_all(self):
        return [_row_to_evi(r) for r in self._c.query(
            f"SELECT {_EVI_COLS} FROM evaluation_instances")]

    def get_completed(self):
        return [_row_to_evi(r) for r in self._c.query(
            f"SELECT {_EVI_COLS} FROM evaluation_instances"
            " WHERE status='EVALCOMPLETED' ORDER BY start_time DESC")]

    def update(self, i: EvaluationInstance) -> bool:
        with self._c.tx() as c:
            cur = c.execute(
                "UPDATE evaluation_instances SET status=?, start_time=?,"
                " end_time=?, evaluation_class=?,"
                " engine_params_generator_class=?, batch=?, env=?,"
                " evaluator_results=?, evaluator_results_html=?,"
                " evaluator_results_json=? WHERE id=?",
                (i.status, _ts(i.start_time), _ts(i.end_time),
                 i.evaluation_class, i.engine_params_generator_class, i.batch,
                 json.dumps(i.env), i.evaluator_results,
                 i.evaluator_results_html, i.evaluator_results_json, i.id))
            return cur.rowcount > 0

    def delete(self, iid) -> bool:
        with self._c.tx() as c:
            cur = c.execute("DELETE FROM evaluation_instances WHERE id=?",
                            (iid,))
            return cur.rowcount > 0


class SqliteModels(_SqliteMetaDAO, base.Models):

    def insert(self, m: Model) -> None:
        with self._c.tx() as c:
            c.execute("INSERT OR REPLACE INTO models (id, models) VALUES (?,?)",
                      (m.id, m.models))

    def get(self, mid):
        row = self._c.query_one(
            "SELECT id, models FROM models WHERE id=?", (mid,))
        return Model(row[0], row[1]) if row else None

    def delete(self, mid) -> bool:
        with self._c.tx() as c:
            cur = c.execute("DELETE FROM models WHERE id=?", (mid,))
            return cur.rowcount > 0


def new_ei_id(prefix: str = "ei") -> str:
    import uuid
    return f"{prefix}_{uuid.uuid4().hex[:16]}"
