"""Property bags attached to events and entities.

Parity target: reference ``data/src/main/scala/io/prediction/data/storage/
DataMap.scala`` (JSON-backed ``DataMap`` with typed ``get``/``getOpt``/
``++``/``--``), ``PropertyMap.scala`` (adds first/lastUpdated timestamps) and
``EntityMap.scala`` (adds entity-ID remapping for matrix indexing).

Design: instead of wrapping a json4s AST we wrap plain Python values
(anything ``json``-serializable). Typed access is by example type, with
conversion errors raised as ``DataMapError``.
"""

from __future__ import annotations

import datetime as _dt
import json
import typing as _t
from typing import Any, Dict, Iterator, Mapping, Optional, Sequence


class DataMapError(KeyError):
    """Missing field or wrong type in a DataMap (cf. DataMapException)."""


def _convert(value: Any, typ: Optional[type]) -> Any:
    if typ is None or typ is object:
        return value
    if typ is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise DataMapError(f"cannot convert {value!r} to float")
        return float(value)
    if typ is int:
        if isinstance(value, bool) or not isinstance(value, int):
            if isinstance(value, float) and value.is_integer():
                return int(value)
            raise DataMapError(f"cannot convert {value!r} to int")
        return int(value)
    if typ is bool:
        if not isinstance(value, bool):
            raise DataMapError(f"cannot convert {value!r} to bool")
        return value
    if typ is str:
        if not isinstance(value, str):
            raise DataMapError(f"cannot convert {value!r} to str")
        return value
    if typ is list:
        if not isinstance(value, (list, tuple)):
            raise DataMapError(f"cannot convert {value!r} to list")
        return list(value)
    if typ is _dt.datetime:
        if isinstance(value, _dt.datetime):
            return value
        if isinstance(value, str):
            from predictionio_tpu.utils.compat import parse_iso8601

            try:
                return parse_iso8601(value)
            except ValueError as e:
                raise DataMapError(
                    f"cannot convert {value!r} to datetime") from e
        raise DataMapError(f"cannot convert {value!r} to datetime")
    if isinstance(value, typ):
        return value
    raise DataMapError(f"cannot convert {value!r} to {typ}")


class DataMap(Mapping[str, Any]):
    """Immutable string-keyed property bag.

    Mirrors reference ``DataMap`` behavior: ``get`` raises on a missing
    field, ``get_opt`` returns None, ``++``/``--`` become ``merged``/
    ``without`` (and the ``|`` / ``-`` operators).
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        self._fields: Dict[str, Any] = dict(fields or {})

    # -- Mapping protocol -------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    # -- typed access -----------------------------------------------------
    def require(self, name: str) -> None:
        if name not in self._fields:
            raise DataMapError(f"The field {name} is required.")

    _NO_TYP = object()

    def get(self, name: str, typ: Any = _NO_TYP, default: Any = ...) -> Any:
        """Typed get; raises DataMapError when missing unless a default is given.

        Also honors ``Mapping.get``-style calls: a non-type second positional
        argument (including None) is treated as the default — ``dm.get('k', 0)``
        returns 0 when 'k' is absent. One deliberate divergence from Mapping:
        a field explicitly present with value None counts as ABSENT (returns
        the default) — parity with the reference, where json4s JNull extracts
        as missing (DataMap.scala get/getOpt).
        """
        if typ is DataMap._NO_TYP:
            typ = None
        elif not isinstance(typ, type) and typ is not None:
            # typing generics (Optional[int], List[str], ...) look like
            # defaults to isinstance — reject loudly instead of silently
            # disabling validation.
            if (getattr(typ, "__module__", None) == "typing"
                    or _t.get_origin(typ) is not None):
                raise TypeError(
                    f"get() does not support typing generics, got {typ!r}; "
                    f"use a concrete type (int, float, str, list, ...)")
            if default is not ...:
                raise TypeError(f"get() type argument must be a type, "
                                f"got {typ!r}")
            typ, default = None, typ
        elif typ is None and default is ...:
            default = None  # Mapping.get(key, None)
        if name not in self._fields or self._fields[name] is None:
            if default is not ...:
                return default
            raise DataMapError(f"The field {name} is required.")
        return _convert(self._fields[name], typ)

    def get_opt(self, name: str, typ: Optional[type] = None) -> Optional[Any]:
        if name not in self._fields or self._fields[name] is None:
            return None
        return _convert(self._fields[name], typ)

    def get_list(self, name: str) -> list:
        return self.get(name, list)

    @property
    def fields(self) -> Dict[str, Any]:
        return dict(self._fields)

    def keySet(self) -> set:  # reference-API spelling, kept for parity
        return set(self._fields)

    @property
    def is_empty(self) -> bool:
        return not self._fields

    # -- combination (DataMap.scala ++ / --) ------------------------------
    def merged(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        out = dict(self._fields)
        out.update(dict(other))
        return DataMap(out)

    def without(self, keys: Sequence[str]) -> "DataMap":
        out = {k: v for k, v in self._fields.items() if k not in set(keys)}
        return DataMap(out)

    __or__ = merged
    __sub__ = without

    # -- serialization ----------------------------------------------------
    def to_json(self) -> str:
        # allow_nan=False: NaN/Infinity are not JSON; letting them through
        # would poison every downstream JSON consumer (sqlite json_extract
        # aborts whole scans on a single malformed row)
        try:
            return json.dumps(self._fields, sort_keys=True,
                              default=_json_default, allow_nan=False)
        except ValueError as e:
            raise DataMapError(
                f"properties contain a non-JSON number (NaN/Infinity): {e}"
            ) from e

    @classmethod
    def from_json(cls, s: str) -> "DataMap":
        return cls(json.loads(s))

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:  # pragma: no cover - rarely used
        return hash(self.to_json())

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"


def _json_default(o: Any) -> Any:
    if isinstance(o, _dt.datetime):
        return o.isoformat()
    raise TypeError(f"not JSON serializable: {o!r}")


class PropertyMap(DataMap):
    """DataMap plus first/last updated times (cf. PropertyMap.scala)."""

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]] = None,
        first_updated: Optional[_dt.datetime] = None,
        last_updated: Optional[_dt.datetime] = None,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self._fields == other._fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    __hash__ = DataMap.__hash__

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self._fields!r}, first_updated={self.first_updated!r}, "
            f"last_updated={self.last_updated!r})"
        )


class EntityMap:
    """Map of entityId -> value plus a stable integer index per entity.

    Parity: reference ``EntityMap.scala`` — used to remap string entity IDs
    onto dense matrix rows. The index ordering is insertion order of the
    supplied mapping (deterministic).
    """

    def __init__(self, data: Mapping[str, Any]):
        self._data = dict(data)
        self._ids = {eid: i for i, eid in enumerate(self._data)}
        self._rev = {i: eid for eid, i in self._ids.items()}

    def __getitem__(self, entity_id: str) -> Any:
        return self._data[entity_id]

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, entity_id: str) -> bool:
        return entity_id in self._data

    def entity_ids(self) -> list:
        return list(self._data)

    def index_of(self, entity_id: str) -> int:
        return self._ids[entity_id]

    def entity_of(self, index: int) -> str:
        return self._rev[index]
