"""Recommendation template (ALS) — parity with
``examples/scala-parallel-recommendation`` (SURVEY §2.5 row 1)."""

from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm,
    ALSModel,
    DataSourceParams,
    EventDataSource,
    ItemScore,
    PredictedResult,
    Query,
    RatingsPreparator,
    RecommendationServing,
    TrainingData,
    engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "ALSModel",
    "DataSourceParams",
    "EventDataSource",
    "ItemScore",
    "PredictedResult",
    "Query",
    "RatingsPreparator",
    "RecommendationServing",
    "TrainingData",
    "engine_factory",
]
