"""Recommendation template (ALS) — parity with
``examples/scala-parallel-recommendation`` (SURVEY §2.5 row 1)."""

from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm,
    ALSModel,
    ALSShardedAlgorithm,
    DataSourceParams,
    EventDataSource,
    ItemScore,
    PrecisionAtK,
    PredictedResult,
    PreparatorParams,
    Query,
    RatingsPreparator,
    RecommendationEvaluation,
    RecommendationParamsList,
    RecommendationServing,
    ShardedALSModel,
    TrainingData,
    engine_factory,
    sharded_engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "ALSModel",
    "ALSShardedAlgorithm",
    "DataSourceParams",
    "EventDataSource",
    "ItemScore",
    "PrecisionAtK",
    "PredictedResult",
    "PreparatorParams",
    "Query",
    "RatingsPreparator",
    "RecommendationEvaluation",
    "RecommendationParamsList",
    "RecommendationServing",
    "ShardedALSModel",
    "TrainingData",
    "engine_factory",
    "sharded_engine_factory",
]
