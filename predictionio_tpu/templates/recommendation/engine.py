"""Recommendation engine: rate events -> implicit ALS -> top-N items.

Capability parity with ``examples/scala-parallel-recommendation`` (the
driver's north-star workload, BASELINE.md):

- DataSource reads ``rate``/``view`` events via PEventStore
  (``custom-query/src/main/scala/DataSource.scala:31-65``)
- Preparator indexes entity IDs with BiMap and pads ratings into the
  TPU layout (``Preparator.scala`` + BiMap.scala:63-129)
- ALSAlgorithm trains implicit ALS on the mesh
  (``ALSAlgorithm.scala:64-103``: rank/iters/lambda/seed, alpha=1.0)
- predict: per-user dot-product top-N with optional seen-item blacklist;
  item-similarity cosine scoring available for item queries
- Serving returns the first algorithm's result

The model is a P2L product: factors come back to host numpy and pickle
cleanly into the Models repository (persistence mode 1).
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    LFirstServing,
    LServing,
    OptionAverageMetric,
    P2LAlgorithm,
    PAlgorithm,
    Params,
    PDataSource,
    PPreparator,
)
from predictionio_tpu.core.context import ComputeContext
from predictionio_tpu.data.bimap import BiMap, StringIndexBiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.als import (
    ALSParams,
    PaddedRatings,
    pad_ratings,
)


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    """``streaming_block_size`` switches the read to the scale-ingest
    path: columnar blocks streamed through an incremental indexer, so a
    10–20M-rating store is never materialized as whole-store object
    columns (SURVEY hard part #2); None keeps the single-scan read."""

    app_name: str
    event_names: Tuple[str, ...] = ("rate",)
    channel_name: Optional[str] = None
    streaming_block_size: Optional[int] = None
    # pipelined flavor of the streaming read: per-block sort while
    # decode runs, merge-based finalize (identical training inputs,
    # see data/columnar.PipelinedRatingsBuilder); decode_prefetch is
    # passed to the backend as its read-ahead hint (jsonlfs decodes
    # that many partitions in parallel)
    pipelined_ingest: bool = False
    decode_prefetch: int = 0
    # filter-by-category variant: also aggregate item $set categories so
    # queries can restrict recommendations to categories
    # (filter-by-category/.../DataSource.scala:60-79)
    read_item_categories: bool = False
    # sliding-window evaluation (the mlc movielens-evaluation example's
    # EventsSlidingEvalParams: firstTrainingUntilTime / evalDuration /
    # evalCount): eval set k trains on events before
    # first_until + k*duration and tests on the following window.
    # eval_count = 0 keeps the default leave-last-out protocol.
    eval_first_until: Optional[str] = None   # ISO-8601
    eval_duration_days: float = 7.0
    eval_count: int = 0


@dataclasses.dataclass
class Rating:
    user: str
    item: str
    rating: float


class TrainingData:
    """Columnar rating triples (users/items as object arrays, float32
    values) — the TPU ingest format. Accepts a ``Rating`` list for parity
    with the reference template's ``TrainingData(ratings: RDD[Rating])``
    (``DataSource.scala:62-65``); ``.ratings`` materializes lazily."""

    def __init__(self, ratings: Optional[List[Rating]] = None, *,
                 users: Optional[np.ndarray] = None,
                 items: Optional[np.ndarray] = None,
                 values: Optional[np.ndarray] = None):
        if ratings is not None:
            n = len(ratings)
            users = np.asarray([r.user for r in ratings], dtype=object)
            items = np.asarray([r.item for r in ratings], dtype=object)
            values = np.fromiter((r.rating for r in ratings),
                                 dtype=np.float32, count=n)
        self.users = users if users is not None \
            else np.empty(0, dtype=object)
        self.items = items if items is not None \
            else np.empty(0, dtype=object)
        self.values = values if values is not None \
            else np.empty(0, dtype=np.float32)
        if not (len(self.users) == len(self.items) == len(self.values)):
            raise ValueError(
                f"misaligned rating columns: {len(self.users)} users, "
                f"{len(self.items)} items, {len(self.values)} values")
        self.item_categories: Optional[Dict[str, Tuple[str, ...]]] = None
        # a None id would become the literal string 'None' at indexing time
        # and train a phantom row/column (cf. ColumnarEvents.encode_entities)
        for name, col in (("user", self.users), ("item", self.items)):
            missing = np.fromiter((x is None for x in col), dtype=bool,
                                  count=len(col))
            if missing.any():
                raise ValueError(
                    f"TrainingData has events without a {name} id; filter "
                    "the event scan (e.g. by target_entity_type)")
        self._ratings: Optional[List[Rating]] = ratings

    @property
    def ratings(self) -> List[Rating]:
        if self._ratings is None:
            self._ratings = [
                Rating(str(u), str(i), float(v))
                for u, i, v in zip(self.users, self.items, self.values)]
        return self._ratings

    def __len__(self) -> int:
        return int(self.users.shape[0])

    def sanity_check(self) -> None:
        assert len(self), (
            "ratings in TrainingData cannot be empty. Please check if "
            "DataSource generates TrainingData correctly.")


def _training_data_prechecked(users: np.ndarray, items: np.ndarray,
                              values: np.ndarray) -> "TrainingData":
    """TrainingData from columns ALREADY validated for None ids —
    sliding eval slices one validated batch per window and must not
    re-pay the O(n) scan eval_count times."""
    td = TrainingData.__new__(TrainingData)
    td.users = users
    td.items = items
    td.values = values
    td.item_categories = None
    td._ratings = None
    return td


class IndexedTrainingData:
    """Already-indexed rating triples from the streaming ingest: dense
    int64 user/item codes plus their BiMaps. The Preparator recognizes
    this and skips re-indexing (the whole point — the string columns
    were never materialized)."""

    def __init__(self, user_map: StringIndexBiMap,
                 item_map: StringIndexBiMap, rows: np.ndarray,
                 cols: np.ndarray, values: np.ndarray):
        self.user_map = user_map
        self.item_map = item_map
        self.rows = rows
        self.cols = cols
        self.values = values
        self.item_categories: Optional[Dict[str, Tuple[str, ...]]] = None

    def __len__(self) -> int:
        return int(self.rows.shape[0])

    def sanity_check(self) -> None:
        assert len(self), (
            "ratings in TrainingData cannot be empty. Please check if "
            "DataSource generates TrainingData correctly.")


class EventDataSource(PDataSource):
    """Reads rating events (DataSource.scala:31-65): rate -> property
    'rating', view -> implicit count of 1. Uses the columnar bulk-read
    path so no per-event Python objects are built; with
    ``streaming_block_size`` set, the read streams bounded blocks
    through an incremental indexer (the partitioned-read analog of
    JDBCPEvents.scala:31-100)."""

    params_class = DataSourceParams

    def read_training(self, ctx: ComputeContext) -> Any:
        return self._read_training(pipelined=None)

    def _read_training(self, pipelined: Optional[bool]) -> Any:
        """``pipelined=None`` follows params; ``False`` forces the
        serial builder (read_eval: its leave-last-out split consumes
        RAW triple order without dedup, and the pipelined finalize
        returns merged (row, col) order — eval must see the same
        stream order as the serial path)."""
        p: DataSourceParams = self.params
        if p.pipelined_ingest and not p.streaming_block_size:
            raise ValueError(
                "pipelined_ingest requires streaming_block_size: the "
                "pipelined builder consumes streamed columnar blocks "
                "(set datasource {\"streamingBlockSize\": N} alongside "
                "\"pipelinedIngest\": true)")
        if pipelined is None:
            pipelined = bool(p.pipelined_ingest)
        if p.streaming_block_size:
            from predictionio_tpu.data.columnar import (
                PipelinedRatingsBuilder,
                StreamingRatingsBuilder,
                iter_blocks_threaded,
            )

            builder = (PipelinedRatingsBuilder() if pipelined
                       else StreamingRatingsBuilder())
            # decode thread + indexing consumer overlap (bounded queue)
            for block in iter_blocks_threaded(
                    PEventStore.find_columnar_blocks(
                        app_name=p.app_name,
                        channel_name=p.channel_name,
                        entity_type="user",
                        event_names=list(p.event_names),
                        target_entity_type="item",
                        value_property="rating",
                        default_value=1.0,
                        block_size=int(p.streaming_block_size),
                        prefetch=int(p.decode_prefetch))):
                builder.add_block(block)
            td = IndexedTrainingData(*builder.finalize())
            td.item_categories = self._read_item_categories(p)
            return td
        batch = PEventStore.find_columnar(
            app_name=p.app_name,
            channel_name=p.channel_name,
            entity_type="user",
            event_names=list(p.event_names),
            target_entity_type="item",
            value_property="rating",
            default_value=1.0,
        )
        td = TrainingData(users=batch.entity_ids, items=batch.target_ids,
                          values=batch.values)
        td.item_categories = self._read_item_categories(p)
        return td

    @staticmethod
    def _read_item_categories(p: DataSourceParams):
        """$set item categories (filter-by-category DataSource.scala:
        60-79); None when the variant flag is off."""
        if not p.read_item_categories:
            return None
        return {
            iid: tuple(pm.get_opt("categories", list) or ())
            for iid, pm in PEventStore.aggregate_properties(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="item").items()
        }

    def read_eval(self, ctx: ComputeContext):
        """Default: leave-last-out per user (readEval analog in the
        template's evaluation variant). With ``eval_count`` > 0:
        time-sliding windows (train on everything before the cut, test
        on the next window — EventsSlidingEvalParams semantics from the
        reference's movielens-evaluation example)."""
        p: DataSourceParams = self.params
        if p.eval_count > 0:
            return self._sliding_eval(p)
        # serial builder even under pipelined_ingest: leave-last-out
        # splits on raw triple ORDER, which the pipelined finalize
        # does not preserve (merged (row, col) order)
        from predictionio_tpu.data.sliding import leave_last_out

        td = self._read_training(pipelined=False)
        if isinstance(td, IndexedTrainingData):
            # eval works on typed ratings; decode the streamed triples
            td = TrainingData(users=td.user_map.decode(td.rows),
                              items=td.item_map.decode(td.cols),
                              values=td.values)
        by_user: Dict[str, List[Rating]] = {}
        for r in td.ratings:
            by_user.setdefault(r.user, []).append(r)
        train, holdouts = leave_last_out(by_user)
        qa = [(Query(user=user, num=10), ActualResult([held.item]))
              for user, held in holdouts]
        return [(TrainingData(train), EmptyEvalInfo(), qa)]

    def _sliding_eval(self, p: DataSourceParams):
        """Sliding time windows: for k in range(eval_count), train on
        events before ``first_until + k*duration`` and hold out each
        user's items in the following window as actuals."""
        import datetime as _dt

        from predictionio_tpu.data.event import _parse_time

        if not p.eval_first_until:
            raise ValueError(
                "eval_count > 0 requires eval_first_until (ISO-8601)")
        if p.streaming_block_size:
            raise ValueError(
                "sliding-window eval materializes the scanned window and "
                "is incompatible with streaming_block_size; drop one of "
                "the two (the scan is bounded to the eval horizon)")
        from predictionio_tpu.data.sliding import sliding_window_masks

        first_until = _parse_time(p.eval_first_until)
        t0 = first_until.timestamp()
        dur = float(p.eval_duration_days) * 86400.0
        horizon = first_until + _dt.timedelta(
            seconds=dur * int(p.eval_count))
        # the scan never needs events past the last test window
        batch = PEventStore.find_columnar(
            app_name=p.app_name, channel_name=p.channel_name,
            entity_type="user", event_names=list(p.event_names),
            target_entity_type="item", value_property="rating",
            default_value=1.0, until_time=horizon)
        # validate the id columns ONCE; per-window slices reuse them
        probe = TrainingData(users=batch.entity_ids,
                             items=batch.target_ids, values=batch.values)
        del probe
        times = batch.event_times
        sets = []
        for k, train_mask, test_mask in sliding_window_masks(
                times, t0, dur, int(p.eval_count),
                hint="move eval_first_until later or reduce eval_count"):
            td = _training_data_prechecked(
                batch.entity_ids[train_mask],
                batch.target_ids[train_mask],
                batch.values[train_mask])
            held: Dict[str, List[str]] = {}
            for u, i in zip(batch.entity_ids[test_mask],
                            batch.target_ids[test_mask]):
                held.setdefault(str(u), []).append(str(i))
            qa = [(Query(user=u, num=10), ActualResult(items))
                  for u, items in held.items()]
            sets.append((td, EmptyEvalInfo(), qa))
        return sets


@dataclasses.dataclass(frozen=True)
class EmptyEvalInfo:
    pass


@dataclasses.dataclass(frozen=True)
class Query:
    """Top-N query: by user (personal recs) or by items (similarity)."""

    user: Optional[str] = None
    items: Tuple[str, ...] = ()
    num: int = 10
    blacklist: Tuple[str, ...] = ()
    # filter-by-category variant: only items in these categories
    # (filter-by-category/.../Engine.scala query field)
    categories: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...]


@dataclasses.dataclass(frozen=True)
class ActualResult:
    items: Tuple[str, ...]

    def __init__(self, items: Sequence[str]):
        object.__setattr__(self, "items", tuple(items))


@dataclasses.dataclass
class PreparedData:
    """BiMap-indexed, TPU-padded ratings."""

    user_map: StringIndexBiMap
    item_map: StringIndexBiMap
    user_side: PaddedRatings
    item_side: PaddedRatings
    seen: Dict[int, np.ndarray]  # user idx -> item idx array (for blacklist)
    # filter-by-category variant: item idx -> categories (None = unread)
    item_categories: Optional[Dict[int, Tuple[str, ...]]] = None

    def sanity_check(self) -> None:
        assert self.user_side.n_rows > 0, "no users after indexing"
        assert self.user_side.n_cols > 0, "no items after indexing"


@dataclasses.dataclass(frozen=True)
class PreparatorParams(Params):
    """``bucketed=True`` lays the ratings out as length buckets
    (``ops.als.bucket_ratings_pair``): each row pads only to its own
    length class, so the solves stop multiplying longest-row padding
    AND nothing is truncated — 100% pair coverage at any scale (the
    full-RDD semantics of ``ALS.trainImplicit``). The recommended
    layout at 10M+ events.

    ``max_len`` bounds the padded row length (keeping the
    largest-magnitude ratings per row); with ``bucketed=False`` it is
    what kept the uniform [N, L] table affordable at scale."""

    max_len: Optional[int] = None
    bucketed: bool = False


class RatingsPreparator(PPreparator):
    """BiMap.stringInt indexing + ALX padding (the reference does the BiMap
    step inside ALSAlgorithm.train, ALSAlgorithm.scala:35-36; here it is a
    proper Preparator so multiple algorithms share the layout). Accepts
    either a :class:`TrainingData` (indexes it here) or an
    :class:`IndexedTrainingData` from the streaming ingest (already
    indexed — no whole-store string columns ever existed)."""

    params_class = PreparatorParams

    def prepare(self, ctx: ComputeContext, td: Any) -> PreparedData:
        if isinstance(td, IndexedTrainingData):
            user_map, item_map = td.user_map, td.item_map
            rows = np.asarray(td.rows, dtype=np.int64)
            cols = np.asarray(td.cols, dtype=np.int64)
            vals = np.asarray(td.values, dtype=np.float32)
        else:
            u_labels, rows = np.unique(td.users.astype(str),
                                       return_inverse=True)
            i_labels, cols = np.unique(td.items.astype(str),
                                       return_inverse=True)
            user_map = StringIndexBiMap.from_distinct(u_labels)
            item_map = StringIndexBiMap.from_distinct(i_labels)
            rows = rows.astype(np.int64)
            cols = cols.astype(np.int64)
            vals = np.asarray(td.values, dtype=np.float32)
        n_u, n_i = len(user_map), len(item_map)
        max_len = getattr(self.params, "max_len", None)
        if getattr(self.params, "bucketed", False):
            from predictionio_tpu.ops.als import bucket_ratings_pair

            user_side, item_side = bucket_ratings_pair(
                rows, cols, vals, n_u, n_i, max_len=max_len)
        else:
            user_side = pad_ratings(rows, cols, vals, n_u, n_i,
                                    max_len=max_len)
            item_side = pad_ratings(cols, rows, vals, n_i, n_u,
                                    max_len=max_len)
        # per-user seen-item lists via one stable sort (vs n_u boolean scans)
        order = np.argsort(rows, kind="stable")
        s_rows, s_cols = rows[order], cols[order]
        starts = np.searchsorted(s_rows, np.arange(n_u))
        ends = np.searchsorted(s_rows, np.arange(n_u), side="right")
        seen = {u: s_cols[starts[u]:ends[u]] for u in range(n_u)}
        cats = None
        raw_cats = getattr(td, "item_categories", None)
        if raw_cats is not None:
            cats = {item_map[iid]: tuple(c)
                    for iid, c in raw_cats.items() if iid in item_map}
        return PreparedData(user_map, item_map, user_side, item_side, seen,
                            item_categories=cats)


class _DeviceServedModel:
    """Shared device-serving plumbing: lazy DeviceTopK construction
    (``_make_server`` is the per-flavor hook) and pickling that drops
    the device handles."""

    _server: Any = None

    def device_server(self):
        if self._server is None:
            self._server = self._make_server()
        return self._server

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_server"] = None  # device handles don't pickle
        # derived caches rebuild on demand; keep model blobs lean
        state.pop("_cat_index", None)
        state.pop("_cat_black_cache", None)
        state.pop("_theta_device", None)  # sequentialrec device cache
        return state


@dataclasses.dataclass
class ALSModel(_DeviceServedModel):
    """Host-persistable factors + maps (ALSModel.scala analog; automatic
    persistence — pickles into the Models repo). Serving runs on the
    DEVICE: ``device_server()`` places the factors in HBM behind an
    AOT-compiled top-k program (ops/serving.py); the pickled blob never
    contains device state."""

    user_factors: np.ndarray     # [N, R]
    item_factors: np.ndarray     # [M, R]
    user_map: StringIndexBiMap
    item_map: StringIndexBiMap
    seen: Dict[int, np.ndarray]
    item_categories: Optional[Dict[int, Tuple[str, ...]]] = None
    _server: Any = dataclasses.field(default=None, repr=False, compare=False)

    def _make_server(self):
        # backend policy: host numpy for small host-resident factors
        # (beats any host<->device transport, the reference's in-JVM
        # predict shape), device program otherwise; override with
        # PIO_SERVING_BACKEND=host|device
        from predictionio_tpu.ops.serving import choose_server

        return choose_server(self.user_factors, self.item_factors, self.seen)

    def sanity_check(self) -> None:
        assert np.isfinite(self.user_factors).all(), "non-finite user factors"
        assert np.isfinite(self.item_factors).all(), "non-finite item factors"


def _coerce_query(query: Any) -> Query:
    """Raw JSON query from the server -> typed Query."""
    if isinstance(query, dict):
        return Query(user=query.get("user"),
                     items=tuple(query.get("items", ())),
                     num=int(query.get("num", 10)),
                     blacklist=tuple(query.get("blacklist", ())),
                     categories=tuple(query.get("categories", ())))
    return query


def _winners_to_result(idx, scores, black, num: int,
                       item_map: StringIndexBiMap,
                       positive_only: bool = True) -> PredictedResult:
    """Fetched top-k row -> PredictedResult: drop blacklisted, non-finite
    and (for ALS-style scorers) non-positive scores host-side, clip to
    num. ``math.isfinite`` on the python floats, not ``np.isfinite`` per
    element — this runs once per query of a bulk batch-predict job.

    ``positive_only=False`` keeps negative finite scores: transformer
    logits (the sequentialrec template) are only RELATIVELY calibrated,
    so a user whose unseen-item dot products are all negative still has
    a valid ranking — only the ``-inf`` device masks (padding / seen
    items) must drop. Models opt out via ``serve_positive_scores_only
    = False``; implicit-ALS keeps the historical positive filter."""
    keep = [(i, s) for i, s in zip(idx.tolist(), scores.tolist())
            if i not in black and math.isfinite(s)
            and (s > 0 or not positive_only)][:num]
    if not keep:
        return PredictedResult(())
    items = item_map.decode(np.asarray([i for i, _ in keep],
                                       dtype=np.int64))
    return PredictedResult(tuple(
        ItemScore(item=item, score=s)
        for item, (_, s) in zip(items, keep)))


_CAT_BLACKLIST_CACHE_MAX = 64
_cat_cache_lock = threading.Lock()


def _category_blacklist(model, categories: Tuple[str, ...]) -> set:
    """Item indices OUTSIDE the requested categories (filter-by-category
    ALSAlgorithm.scala:85-101: recommendations restricted to the query
    categories; items without categories are out). The inverted
    category index and the per-categories complement are cached on the
    model — the serving hot path must not pay an O(n_items) Python loop
    per query. The complement cache is a bounded LRU: each entry is
    O(n_items), and a public endpoint can present unboundedly many
    distinct category combinations. Mutations take a lock — the query
    server serves on concurrent threads (ThreadingHTTPServer)."""
    import collections

    with _cat_cache_lock:
        cache = getattr(model, "_cat_black_cache", None)
        if cache is None:
            cache = collections.OrderedDict()
            model._cat_black_cache = cache
        black = cache.get(categories)
        if black is not None:
            cache.move_to_end(categories)
            return black
    index = getattr(model, "_cat_index", None)
    if index is None:
        index = {}
        for ix, cats in model.item_categories.items():
            for c in cats:
                index.setdefault(c, set()).add(ix)
        model._cat_index = index
    eligible: set = set()
    for c in categories:
        eligible |= index.get(c, set())
    black = set(range(len(model.item_map))) - eligible
    with _cat_cache_lock:
        cache[categories] = black
        while len(cache) > _CAT_BLACKLIST_CACHE_MAX:
            cache.popitem(last=False)
    return black


def _serve_topk(server, model, query: Query) -> PredictedResult:
    """Shared device-serving logic for both ALS flavors: ask the compiled
    program for num + |blacklist| winners (seen items already masked on
    device), drop blacklisted/non-positive ones host-side, clip to num.
    A category restriction joins the blacklist (with a full ranking, so
    enough in-category candidates survive the cut)."""
    user_map, item_map = model.user_map, model.item_map
    black = {item_map[i] for i in query.blacklist if i in item_map}
    if query.categories:
        if getattr(model, "item_categories", None) is None:
            raise ValueError(
                "query has categories but the model was trained without "
                "read_item_categories=True on the datasource")
        black = black | _category_blacklist(model, query.categories)
    k = query.num + len(black)
    if query.items:
        idxs = [item_map[i] for i in query.items if i in item_map]
        if not idxs:
            return PredictedResult(())
        idx, scores = server.items_topk(idxs, k)
    elif query.user is not None:
        uidx = user_map.get(query.user)
        if uidx is None:
            return PredictedResult(())
        idx, scores = server.user_topk(uidx, k)
    else:
        return PredictedResult(())
    return _winners_to_result(
        idx, scores, black, query.num, item_map,
        positive_only=getattr(model, "serve_positive_scores_only", True))


class _DeviceServingAlgo:
    """Shared predict/warmup for every ALS flavor served by DeviceTopK."""

    def warmup_base(self, model) -> None:
        """Compile the device top-k buckets at deploy so the first real
        query pays no compile/first-dispatch cost (SURVEY hard part #4)."""
        if len(model.user_map):
            model.device_server().warmup()

    def predict(self, model, query: Query) -> PredictedResult:
        query = _coerce_query(query)
        return _serve_topk(model.device_server(), model, query)

    def _batched_predict(self, model, indexed_queries
                         ) -> List[Tuple[int, Any]]:
        """Batch-predict as ONE device job (P2LAlgorithm.scala:66-68):
        known-user queries are grouped per (num + blacklist) bucket and
        dispatched through `DeviceTopK.users_topk` — one round trip per
        group instead of one per query; item-similarity / unknown-user
        queries fall back to the per-query path."""
        queries = [(qx, _coerce_query(q)) for qx, q in indexed_queries]
        server = model.device_server()
        results: Dict[int, Any] = {}
        # (k needed) -> list of (qx, uidx, blacklist idx set, num)
        groups: Dict[int, List[Tuple[int, int, set, int]]] = {}
        for qx, q in queries:
            # category queries need the full-ranking path in predict()
            uidx = (model.user_map.get(q.user)
                    if q.user is not None and not q.items
                    and not q.categories else None)
            if uidx is None:
                results[qx] = self.predict(model, q)
                continue
            black = {model.item_map[i] for i in q.blacklist
                     if i in model.item_map}
            k = q.num + len(black)
            groups.setdefault(k, []).append((qx, uidx, black, q.num))
        for k, rows in groups.items():
            uids = np.asarray([r[1] for r in rows], dtype=np.int64)
            idx, scores = server.users_topk(uids, k)
            positive = getattr(model, "serve_positive_scores_only", True)
            for row, (qx, _, black, num) in enumerate(rows):
                results[qx] = _winners_to_result(
                    idx[row], scores[row], black, num, model.item_map,
                    positive_only=positive)
        return [(qx, results[qx]) for qx, _ in queries]


class ALSAlgorithm(_DeviceServingAlgo, P2LAlgorithm):
    """Implicit ALS on the TPU mesh (ALSAlgorithm.scala:64-103 parity)."""

    params_class = ALSParams
    query_cls = Query

    def train(self, ctx: ComputeContext, pd: PreparedData) -> ALSModel:
        # topology-aware: sharded over the (multi-host) mesh when one
        # exists, single-device otherwise (parallel/als_sharding.py)
        from predictionio_tpu.parallel.als_sharding import train_als_auto
        from predictionio_tpu.workflow import runlog
        from predictionio_tpu.workflow.checkpoint import (
            bimap_fingerprint_scope)

        # the entity maps join the crash-safe checkpoint fingerprint:
        # two stores with identical table shapes but different entity
        # universes must never resume each other's checkpoints
        # (no-op while checkpointing is off); the run-context scope
        # stamps the run-history header so `pio runs list` can say
        # WHAT trained, not just when
        with bimap_fingerprint_scope(pd.user_map, pd.item_map), \
                runlog.run_context_scope(
                    template="recommendation",
                    nUsers=pd.user_side.n_rows,
                    nItems=pd.user_side.n_cols):
            X, Y = train_als_auto(pd.user_side, pd.item_side, self.params)
        return ALSModel(X, Y, pd.user_map, pd.item_map, pd.seen,
                        item_categories=pd.item_categories)

    def batch_predict(self, ctx: ComputeContext, model: "ALSModel",
                      indexed_queries) -> List[Tuple[int, Any]]:
        return self._batched_predict(model, indexed_queries)


@dataclasses.dataclass
class ShardedALSModel(_DeviceServedModel):
    """Device-RESIDENT model: factor matrices live sharded in HBM
    (padded jax Arrays from ``train_als_device``) and are never gathered
    to host — the PAlgorithm 'model bigger than a host' semantics
    (PAlgorithm.scala:24-45, SURVEY hard part #5). Not picklable by
    design; persistence mode is RETRAIN-at-deploy."""

    user_factors: Any            # jax Array [N_pad, R], sharded
    item_factors: Any            # jax Array [M_pad, R], sharded
    n_users: int
    n_items: int
    user_map: StringIndexBiMap
    item_map: StringIndexBiMap
    seen: Dict[int, np.ndarray]
    item_categories: Optional[Dict[int, Tuple[str, ...]]] = None
    # density-aware shard layout (parallel.als_sharding.ItemShardLayout)
    # carried WITH the model so serving, fold-in, and eval all see one
    # consistent item placement; None serves the training placement
    item_layout: Any = None
    _server: Any = dataclasses.field(default=None, repr=False, compare=False)

    def _make_server(self):
        from predictionio_tpu.ops.serving import DeviceTopK

        return DeviceTopK(
            self.user_factors, self.item_factors, self.seen,
            n_users=self.n_users, n_items=self.n_items,
            item_layout=self.item_layout)

    def sanity_check(self) -> None:
        # finiteness check WITHOUT gathering the factors: reduce on device
        import jax.numpy as jnp

        assert bool(jnp.isfinite(self.user_factors).all()), \
            "non-finite user factors"
        assert bool(jnp.isfinite(self.item_factors).all()), \
            "non-finite item factors"


class ALSShardedAlgorithm(_DeviceServingAlgo, PAlgorithm):
    """PAlgorithm flavor of the ALS template: trains with
    ``train_als_device`` and serves straight from the HBM shards through
    the compiled top-k program — no host copy of the factors exists at
    any point (the reference's RDD-model ALS variant,
    ``examples/scala-parallel-recommendation/custom-query/.../
    ALSAlgorithm.scala:77-103``, where predict runs cluster-side)."""

    params_class = ALSParams
    query_cls = Query

    def train(self, ctx: ComputeContext,
              pd: PreparedData) -> ShardedALSModel:
        import jax

        from predictionio_tpu.ops.als import item_interaction_counts
        from predictionio_tpu.parallel.als_sharding import (
            density_aware_item_layout,
            train_als_device,
        )
        from predictionio_tpu.workflow import runlog
        from predictionio_tpu.workflow.checkpoint import (
            bimap_fingerprint_scope)

        with bimap_fingerprint_scope(pd.user_map, pd.item_map), \
                runlog.run_context_scope(
                    template="recommendation-sharded",
                    nUsers=pd.user_side.n_rows,
                    nItems=pd.user_side.n_cols):
            X, Y = train_als_device(pd.user_side, pd.item_side,
                                    self.params)
        # serving layout: on a multi-device runtime the item store
        # re-places density-aware (greedy bin-pack over the power-law
        # head, ISSUE 15) so no serve shard hot-spots; the layout
        # travels inside the model so fold-in/eval read one placement
        layout = None
        n_dev = len(jax.devices())
        if n_dev > 1:
            layout = density_aware_item_layout(
                item_interaction_counts(pd.item_side), n_dev)
        return ShardedALSModel(
            X, Y, pd.user_side.n_rows, pd.user_side.n_cols,
            pd.user_map, pd.item_map, pd.seen,
            item_categories=pd.item_categories, item_layout=layout)

    def batch_predict(self, ctx: ComputeContext, model: ShardedALSModel,
                      indexed_queries) -> List[Tuple[int, Any]]:
        """Evaluation over the device-resident model: the whole query set
        runs as grouped `users_topk` dispatches against the HBM shards —
        one round trip per group, not per query."""
        return self._batched_predict(model, indexed_queries)


class RecommendationServing(LFirstServing):
    """First-serving (template Serving.scala returns the single result)."""


@dataclasses.dataclass(frozen=True)
class ServingParams(Params):
    """custom-serving variant (its Serving.scala:10): path of a file
    listing disabled product ids, one per line."""

    filepath: str = "disabled.txt"


class FileBlacklistServing(LServing):
    """custom-serving variant: re-read the disabled-products file on
    EVERY query (deliberate in the reference — ops can edit the file
    under a live server) and drop those items from the first
    algorithm's result (custom-serving/.../Serving.scala:13-27)."""

    params_class = ServingParams

    def serve(self, query: Query,
              predictions: List[PredictedResult]) -> PredictedResult:
        import os

        filepath = getattr(self.params, "filepath", "disabled.txt")
        disabled = set()
        if os.path.exists(filepath):
            with open(filepath, "r", encoding="utf-8") as f:
                disabled = {ln.strip() for ln in f if ln.strip()}
        head = predictions[0]
        return PredictedResult(tuple(
            s for s in head.item_scores if s.item not in disabled))


class PrecisionAtK(OptionAverageMetric):
    """Precision@k on top-N recommendations — the BASELINE.md quality
    parity metric (mirrors the reference's movielens evaluation example,
    ``examples/experimental/scala-parallel-recommendation-mlc/``): for
    each (query, predicted, actual), the fraction of the top-k
    recommended items that appear in the held-out actuals; None (skipped)
    when the user has no actuals."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_qpa(self, q: Query, p: PredictedResult,
                      a: ActualResult) -> Optional[float]:
        if not a.items:
            return None
        actual = set(a.items)
        top = [s.item for s in p.item_scores[:self.k]]
        if not top:
            return 0.0
        return sum(1 for i in top if i in actual) / float(self.k)


class NDCGAtK(OptionAverageMetric):
    """NDCG@k on top-N recommendations — the sequence-aware companion
    to :class:`PrecisionAtK` (ROADMAP item-1 follow-on): rank position
    matters, so a model that puts a held-out item first scores higher
    than one that buries it at position k. Shares the binary-relevance
    math with the bench (``data.sliding.ndcg_at_k``)."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"NDCG@{self.k}"

    def calculate_qpa(self, q: Query, p: PredictedResult,
                      a: ActualResult) -> Optional[float]:
        if not a.items:
            return None
        from predictionio_tpu.data.sliding import ndcg_at_k

        return ndcg_at_k([s.item for s in p.item_scores], a.items,
                         self.k)


class RecommendationParamsList(EngineParamsGenerator):
    """Default tuning grid over rank/lambda (EngineParamsGenerator
    analog used by the reference's evaluation templates)."""

    def __init__(self, app_name: str = "recommendation-app"):
        super().__init__()
        self.engine_params_list = [
            EngineParams(
                data_source_params=("", DataSourceParams(app_name=app_name)),
                algorithm_params_list=[
                    ("als", ALSParams(rank=rank, num_iterations=10,
                                      lambda_=lam, seed=3))],
            )
            for rank in (8, 16)
            for lam in (0.01, 0.1)
        ]


class RecommendationEvaluation(Evaluation, RecommendationParamsList):
    """`pio eval` entry: ALS grid scored by Precision@10; best params
    land in best.json (Evaluation.scala engine_metric path).

    Also an EngineParamsGenerator (like the reference's evaluation
    templates that extend both), so ``pio eval <this-class>`` needs no
    separate generator argument and ``app_name`` reaches the
    datasource params of every grid point."""

    def __init__(self, app_name: str = "recommendation-app", k: int = 10):
        Evaluation.__init__(self)
        RecommendationParamsList.__init__(self, app_name=app_name)
        self.engine_metric = (engine_factory(), PrecisionAtK(k))


def engine_factory() -> Engine:
    """EngineFactory analog (custom-query Engine.scala:13-19). The
    custom-serving variant registers FileBlacklistServing under
    "fileblacklist" (select via engine.json serving section)."""
    return Engine(
        EventDataSource,
        RatingsPreparator,
        {"als": ALSAlgorithm, "": ALSAlgorithm},
        {"": RecommendationServing,
         "fileblacklist": FileBlacklistServing},
    )


def sharded_engine_factory() -> Engine:
    """Engine whose model stays sharded in HBM (PAlgorithm flavor) —
    deploy retrains (persistence mode 3) and serves from the device."""
    return Engine(
        EventDataSource,
        RatingsPreparator,
        {"als": ALSShardedAlgorithm, "": ALSShardedAlgorithm},
        RecommendationServing,
    )
