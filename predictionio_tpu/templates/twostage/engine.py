"""Two-stage template: ALS retrieval + seqrec re-rank, one engine.

The first REAL multi-algorithm engine (ISSUE 20): ``EngineParams.
algorithms = [("als", ...), ("seqrec", ...)]`` trains BOTH stages from
one event stream, and :class:`~predictionio_tpu.controller.
TwoStageServing` combines them — fused into one device program on live
deployments (``workflow.create_server`` binds a
:class:`~predictionio_tpu.ops.twostage.TwoStageTopK` over both models'
tables), composed on host in the eval pipeline.

The one Preparator is the load-bearing piece: both stages MUST share
one user map and one item map (candidate positions retrieved by stage
1 index stage 2's embedding table directly in HBM), so
:class:`TwoStagePreparator` indexes the event stream once and lays it
out BOTH ways — ALX-padded rating tables for the ALS half-steps and
time-ordered bucketed sequences for the transformer — wrapped in one
:class:`TwoStagePrepared` that each algorithm unwraps its side of.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from predictionio_tpu.controller import Engine, Params, PPreparator
from predictionio_tpu.controller.controllers import TwoStageServing
from predictionio_tpu.core.context import ComputeContext
from predictionio_tpu.data.bimap import StringIndexBiMap
from predictionio_tpu.ops.als import pad_ratings
from predictionio_tpu.ops.seqrec import bucket_sequences
from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm,
    ALSModel,
    PreparedData,
)
from predictionio_tpu.templates.sequentialrec.engine import (
    PreparedSequences,
    SeqRecAlgorithm,
    SeqRecModel,
    SequenceDataSource,
    SequenceTrainingData,
)


@dataclasses.dataclass(frozen=True)
class TwoStagePreparatorParams(Params):
    """``max_seq_len`` caps the re-ranker's sequence buckets;
    ``max_len`` (optional) caps the ALS rating-row padding."""

    max_seq_len: int = 32
    max_len: Optional[int] = None


@dataclasses.dataclass
class TwoStagePrepared:
    """Both stages' layouts over ONE shared (user, item) index space —
    the invariant the fused candidate handoff depends on."""

    ratings: PreparedData
    sequences: PreparedSequences

    @property
    def user_map(self) -> StringIndexBiMap:
        return self.ratings.user_map

    @property
    def item_map(self) -> StringIndexBiMap:
        return self.ratings.item_map


class TwoStagePreparator(PPreparator):
    """Index the event stream ONCE, lay it out twice.

    Consumes the sequence template's :class:`SequenceTrainingData`
    (user, item, time triples). The ALS side treats each event as an
    implicit rating of 1.0 (repeat events accumulate weight through the
    normal-equations sums, the standard implicit-feedback reading); the
    sequence side time-orders each user's run and buckets it. Both
    sides carry the SAME maps object — the algorithms' models therefore
    agree bit-for-bit about every index, which
    :func:`~predictionio_tpu.ops.twostage.build_two_stage_store`
    re-checks loudly at deploy."""

    params_class = TwoStagePreparatorParams

    def prepare(self, ctx: ComputeContext,
                td: SequenceTrainingData) -> TwoStagePrepared:
        p: TwoStagePreparatorParams = self.params
        u_labels, rows = np.unique(td.users.astype(str),
                                   return_inverse=True)
        i_labels, cols = np.unique(td.items.astype(str),
                                   return_inverse=True)
        user_map = StringIndexBiMap.from_distinct(u_labels)
        item_map = StringIndexBiMap.from_distinct(i_labels)
        rows = rows.astype(np.int64)
        cols = cols.astype(np.int64)
        n_u, n_i = len(user_map), len(item_map)
        vals = np.ones(len(rows), dtype=np.float32)
        user_side = pad_ratings(rows, cols, vals, n_u, n_i,
                                max_len=p.max_len)
        item_side = pad_ratings(cols, rows, vals, n_i, n_u,
                                max_len=p.max_len)
        # time-ordered per-user runs for the sequence side, seen sets
        # for serving — one stable sort each (the source templates'
        # vectorized discipline)
        n = len(td)
        order = np.lexsort((np.arange(n), td.times, rows))
        s_rows, s_cols = rows[order], cols[order]
        starts = np.searchsorted(s_rows, np.arange(n_u))
        ends = np.searchsorted(s_rows, np.arange(n_u), side="right")
        seqs = [s_cols[starts[u]:ends[u]] for u in range(n_u)]
        seen = {u: np.unique(seqs[u]) for u in range(n_u)
                if len(seqs[u])}
        buckets = bucket_sequences(seqs, max_len=int(p.max_seq_len))
        ratings = PreparedData(user_map, item_map, user_side,
                               item_side, seen)
        sequences = PreparedSequences(user_map, item_map, buckets,
                                      seen, int(p.max_seq_len))
        return TwoStagePrepared(ratings, sequences)


class TwoStageALSAlgorithm(ALSAlgorithm):
    """Stage 1 (retrieval): the standard ALS algorithm trained on the
    shared preparation's rating side."""

    def train(self, ctx: ComputeContext,
              pd: TwoStagePrepared) -> ALSModel:
        return super().train(ctx, pd.ratings)


class TwoStageSeqRecAlgorithm(SeqRecAlgorithm):
    """Stage 2 (re-rank): the standard seqrec algorithm trained on the
    shared preparation's sequence side."""

    def train(self, ctx: ComputeContext,
              pd: TwoStagePrepared) -> SeqRecModel:
        return super().train(ctx, pd.sequences)


def engine_factory() -> Engine:
    return Engine(
        SequenceDataSource,
        TwoStagePreparator,
        {"als": TwoStageALSAlgorithm,
         "seqrec": TwoStageSeqRecAlgorithm,
         "": TwoStageALSAlgorithm},
        {"": TwoStageServing},
    )
