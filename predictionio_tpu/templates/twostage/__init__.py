from predictionio_tpu.templates.twostage.engine import (  # noqa: F401
    TwoStageALSAlgorithm,
    TwoStagePrepared,
    TwoStagePreparator,
    TwoStagePreparatorParams,
    TwoStageSeqRecAlgorithm,
    engine_factory,
)
