"""Classification engine: ``$set`` entity properties -> Naive Bayes label.

Capability parity with ``examples/scala-parallel-classification/
add-algorithm``:

- DataSource aggregates user properties, requiring ``plan`` (the label)
  and ``attr0..attr2`` (features) — ``DataSource.scala:31-65``
- ``NaiveBayesAlgorithm`` (P2L) = multinomial NB with additive smoothing,
  numerically identical to MLlib ``NaiveBayes.train(lambda)``
  (``NaiveBayesAlgorithm.scala:16-23``): one vectorized count + log
  instead of an RDD aggregate
- a second registered algorithm (``categorical``, e2
  CategoricalNaiveBayes over stringified features) mirrors the
  template's multi-algorithm "add-algorithm" variant
- k-fold ``read_eval`` via e2 ``split_data`` + an ``Accuracy`` metric
  (the template's evaluation setup)
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Engine,
    LFirstServing,
    P2LAlgorithm,
    Params,
    PDataSource,
    PIdentityPreparator,
)
from predictionio_tpu.controller.metrics import AverageMetric
from predictionio_tpu.core.context import ComputeContext
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.e2 import (
    CategoricalNaiveBayes,
    LabeledPoint as E2LabeledPoint,
    split_data,
)

FEATURE_PROPS = ("attr0", "attr1", "attr2")
LABEL_PROP = "plan"


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str
    channel_name: Optional[str] = None
    eval_k: int = 3


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    label: float
    features: Tuple[float, ...]


@dataclasses.dataclass
class TrainingData:
    labeled_points: List[LabeledPoint]

    def sanity_check(self) -> None:
        assert self.labeled_points, (
            "labeled_points in TrainingData cannot be empty. Please check "
            "if DataSource generates TrainingData correctly.")


@dataclasses.dataclass(frozen=True)
class Query:
    features: Tuple[float, ...] = ()


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: float


@dataclasses.dataclass(frozen=True)
class ActualResult:
    label: float


@dataclasses.dataclass(frozen=True)
class EmptyEvalInfo:
    pass


class EventDataSource(PDataSource):
    """Aggregated user properties -> labeled points
    (DataSource.scala:31-65: required plan/attr0/attr1/attr2)."""

    params_class = DataSourceParams

    def _labeled_points(self) -> List[LabeledPoint]:
        p: DataSourceParams = self.params
        props = PEventStore.aggregate_properties(
            app_name=p.app_name,
            channel_name=p.channel_name,
            entity_type="user",
            required=[LABEL_PROP, *FEATURE_PROPS],
        )
        return [
            LabeledPoint(
                label=pm.get(LABEL_PROP, float),
                features=tuple(pm.get(a, float) for a in FEATURE_PROPS),
            )
            for pm in props.values()
        ]

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        return TrainingData(self._labeled_points())

    def read_eval(self, ctx: ComputeContext):
        """k-fold CV via e2 split_data (CrossValidation.scala:33-64)."""
        p: DataSourceParams = self.params
        return split_data(
            p.eval_k,
            self._labeled_points(),
            EmptyEvalInfo(),
            TrainingData,
            lambda lp: Query(features=lp.features),
            lambda lp: ActualResult(label=lp.label),
        )


@dataclasses.dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0


@dataclasses.dataclass
class NaiveBayesModel:
    """Multinomial NB: log priors pi [L], log likelihood theta [L, F],
    label values [L] (the MLlib NaiveBayesModel fields)."""

    labels: np.ndarray   # [L] float
    pi: np.ndarray       # [L] float
    theta: np.ndarray    # [L, F] float

    def predict_scores(self, features: np.ndarray) -> np.ndarray:
        """[..., F] -> [..., L]: pi + x·thetaᵀ — one matmul, batch-ready."""
        return self.pi + np.asarray(features, dtype=np.float64) @ self.theta.T

    def sanity_check(self) -> None:
        assert np.isfinite(self.pi).all() and np.isfinite(self.theta).all()


class NaiveBayesAlgorithm(P2LAlgorithm):
    """MLlib NaiveBayes.train parity: pi_l = log(n_l + λ) -
    log(n + L·λ); theta_lj = log(sum_j x_j + λ) - log(sum_all + F·λ)."""

    params_class = NaiveBayesParams
    query_cls = Query

    def train(self, ctx: ComputeContext, pd: TrainingData) -> NaiveBayesModel:
        lam = self.params.lambda_
        pts = pd.labeled_points
        X = np.asarray([p.features for p in pts], dtype=np.float64)
        y = np.asarray([p.label for p in pts], dtype=np.float64)
        if np.any(X < 0):
            raise ValueError("multinomial NB requires non-negative features")
        labels = np.unique(y)
        L, F = len(labels), X.shape[1]
        codes = np.searchsorted(labels, y)
        counts = np.bincount(codes, minlength=L).astype(np.float64)
        pi = np.log(counts + lam) - np.log(len(pts) + L * lam)
        sums = np.zeros((L, F), dtype=np.float64)
        np.add.at(sums, codes, X)
        theta = (np.log(sums + lam)
                 - np.log(sums.sum(axis=1, keepdims=True) + F * lam))
        return NaiveBayesModel(labels=labels, pi=pi, theta=theta)

    def predict(self, model: NaiveBayesModel, query: Query) -> PredictedResult:
        scores = model.predict_scores(
            np.asarray(query.features, dtype=np.float64))
        return PredictedResult(label=float(model.labels[np.argmax(scores)]))

    def batch_predict(self, ctx: ComputeContext, model: NaiveBayesModel,
                      indexed_queries: Sequence[Tuple[int, Query]]):
        """One batched matmul for the whole eval query set (replaces the
        reference's default per-query mapValues)."""
        if not indexed_queries:
            return []
        X = np.asarray([q.features for _, q in indexed_queries],
                       dtype=np.float64)
        best = np.argmax(model.predict_scores(X), axis=1)
        return [
            (qx, PredictedResult(label=float(model.labels[b])))
            for (qx, _), b in zip(indexed_queries, best)
        ]


class CategoricalNBAlgorithm(P2LAlgorithm):
    """Second algorithm (the "add-algorithm" variant slot): e2 categorical
    NB over stringified feature values."""

    params_class = None
    query_cls = Query

    def train(self, ctx: ComputeContext, pd: TrainingData):
        points = [
            E2LabeledPoint(label=str(p.label),
                           features=tuple(str(f) for f in p.features))
            for p in pd.labeled_points
        ]
        return CategoricalNaiveBayes.train(points)

    def predict(self, model, query: Query) -> PredictedResult:
        label = model.predict(tuple(str(f) for f in query.features))
        return PredictedResult(label=float(label))


@dataclasses.dataclass(frozen=True)
class RandomForestParams(Params):
    """RandomForestAlgorithmParams 1:1
    (add-algorithm/src/main/scala/RandomForestAlgorithm.scala:12-19)."""

    num_classes: int = 2
    num_trees: int = 10
    feature_subset_strategy: str = "auto"
    impurity: str = "gini"
    max_depth: int = 5
    max_bins: int = 32
    seed: Optional[int] = None


class RandomForestAlgorithm(P2LAlgorithm):
    """Random forest over the same labeled points
    (RandomForestAlgorithm.scala:23-50; the MLlib dependency is replaced
    by e2/forest.py's vectorized implementation)."""

    params_class = RandomForestParams
    query_cls = Query

    def train(self, ctx: ComputeContext, pd: TrainingData):
        from predictionio_tpu.e2.forest import train_classifier

        p: RandomForestParams = self.params
        X = np.asarray([lp.features for lp in pd.labeled_points],
                       dtype=np.float64)
        y_float = np.asarray([lp.label for lp in pd.labeled_points],
                             dtype=np.float64)
        y = y_float.astype(np.int64)
        if not (y == y_float).all():
            # int64 cast would silently truncate (e.g. label 1.5 -> 1)
            bad = sorted(set(y_float[y != y_float].tolist()))
            raise ValueError(
                f"random forest labels must be integers in "
                f"[0, num_classes); got non-integer labels {bad[:5]}")
        return train_classifier(
            X, y, num_classes=p.num_classes, num_trees=p.num_trees,
            feature_subset_strategy=p.feature_subset_strategy,
            impurity=p.impurity, max_depth=p.max_depth,
            max_bins=p.max_bins, seed=p.seed)

    def predict(self, model, query: Query) -> PredictedResult:
        return PredictedResult(label=model.predict(query.features))

    def batch_predict(self, ctx: ComputeContext, model,
                      indexed_queries) -> List[Tuple[int, Any]]:
        X = np.asarray([q.features for _, q in indexed_queries],
                       dtype=np.float64)
        labels = model.predict_batch(X)
        return [(qx, PredictedResult(label=float(lb)))
                for (qx, _), lb in zip(indexed_queries, labels)]


class Accuracy(AverageMetric):
    """Fraction of exact label matches (the template's evaluation metric)."""

    def calculate_qpa(self, q, p, a) -> float:
        return 1.0 if p.label == a.label else 0.0


def engine_factory() -> Engine:
    """ClassificationEngine (add-algorithm Engine.scala:60-68)."""
    return Engine(
        EventDataSource,
        PIdentityPreparator,
        {"naive": NaiveBayesAlgorithm,
         "categorical": CategoricalNBAlgorithm,
         "randomforest": RandomForestAlgorithm,
         "": NaiveBayesAlgorithm},
        LFirstServing,
    )
