"""Classification engine template (Naive Bayes on entity properties)."""

from predictionio_tpu.templates.classification.engine import (  # noqa: F401
    Accuracy,
    CategoricalNBAlgorithm,
    DataSourceParams,
    EventDataSource,
    LabeledPoint,
    NaiveBayesAlgorithm,
    NaiveBayesModel,
    NaiveBayesParams,
    PredictedResult,
    Query,
    RandomForestAlgorithm,
    RandomForestParams,
    TrainingData,
    engine_factory,
)
