"""Local linear-regression engine template (experimental family)."""

from predictionio_tpu.templates.regression.engine import (  # noqa: F401
    DataSourceParams,
    LocalAlgorithm,
    LocalDataSource,
    LocalPreparator,
    MeanSquareError,
    PreparatorParams,
    Query,
    TrainingData,
    engine_factory,
)
