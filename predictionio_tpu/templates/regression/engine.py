"""Local linear-regression engine — the experimental L-flavor template
with a real Preparator and an eval metric.

Capability parity with the reference's
``examples/experimental/scala-local-regression/Run.scala``:

- ``LocalDataSource`` reads ``y x1 x2 ...`` lines from a file; the one
  eval set pairs every feature row with its target (``Run.scala:37-51``)
- ``LocalPreparator`` drops rows whose index ≡ k (mod n) when n > 0 —
  the template's toy train/test split knob (``Run.scala:55-67``)
- ``LocalAlgorithm`` fits ordinary least squares (the reference calls
  nak's ``LinearRegression.regress``; here ``np.linalg.lstsq``); the
  model is the coefficient vector, predict is a dot product
  (``Run.scala:69-86``)
- ``MeanSquareError`` scores (query, prediction, actual) triples
  (the reference wires ``classOf[MeanSquareError]``, ``Run.scala:135``)

Queries arrive as ``{"features": [...]}`` objects (the reference's
custom ``VectorSerializer`` accepted bare arrays, ``Run.scala:91-103``,
but this framework's query server takes JSON objects).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Engine,
    LAlgorithm,
    LDataSource,
    LFirstServing,
    LPreparator,
    Params,
)
from predictionio_tpu.controller.metrics import AverageMetric


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    filepath: str


@dataclasses.dataclass
class TrainingData:
    """x [n, d], y [n] (TrainingData at Run.scala:29-32)."""

    x: np.ndarray
    y: np.ndarray

    def sanity_check(self) -> None:
        assert len(self.x), "regression training data cannot be empty"
        assert len(self.x) == len(self.y), "misaligned x/y"


@dataclasses.dataclass(frozen=True)
class Query:
    """A feature vector; wire form ``{"features": [...]}``."""

    features: Tuple[float, ...] = ()


class LocalDataSource(LDataSource):
    """``y x1 x2 ...`` file -> one eval set (Run.scala:34-51)."""

    params_class = DataSourceParams

    def _read(self) -> TrainingData:
        p: DataSourceParams = self.params
        xs: List[List[float]] = []
        ys: List[float] = []
        with open(p.filepath, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                ys.append(float(parts[0]))
                xs.append([float(v) for v in parts[1:]])
        return TrainingData(np.asarray(xs, dtype=np.float64),
                            np.asarray(ys, dtype=np.float64))

    def read_training(self) -> TrainingData:
        return self._read()

    def read_eval(self):
        td = self._read()
        qa = [(Query(tuple(row)), float(target))
              for row, target in zip(td.x, td.y)]
        return [(td, "The One", qa)]


@dataclasses.dataclass(frozen=True)
class PreparatorParams(Params):
    """n = 0 keeps everything; n > 0 drops rows with index % n == k
    (Run.scala:53-55)."""

    n: int = 0
    k: int = 0


class LocalPreparator(LPreparator):
    params_class = PreparatorParams

    def prepare(self, td: TrainingData) -> TrainingData:
        p: PreparatorParams = self.params
        if p.n <= 0:
            return td
        keep = np.arange(len(td.y)) % p.n != p.k
        return TrainingData(td.x[keep], td.y[keep])


class LocalAlgorithm(LAlgorithm):
    """OLS fit; model = coefficient vector (Run.scala:69-86)."""

    query_cls = Query

    def train(self, td: TrainingData) -> np.ndarray:
        coef, *_ = np.linalg.lstsq(td.x, td.y, rcond=None)
        return coef

    def predict(self, model: np.ndarray, query: Query) -> float:
        return float(np.dot(model, np.asarray(query.features,
                                              dtype=np.float64)))


class MeanSquareError(AverageMetric):
    """MSE over (Q, P, A) triples (controller MeanSquareError analog the
    reference wires as its evaluator, Run.scala:135)."""

    @property
    def header(self) -> str:
        return "MeanSquareError"

    def calculate_qpa(self, q, p, a) -> float:
        return float((p - a) ** 2)

    def compare(self, a: float, b: float) -> int:
        # smaller error wins (AverageMetric defaults to bigger-is-better)
        return (b > a) - (b < a)


def engine_factory() -> Engine:
    """RegressionEngineFactory (Run.scala:105-113)."""
    return Engine(
        LocalDataSource,
        LocalPreparator,
        {"": LocalAlgorithm},
        LFirstServing,
    )
