"""Hello-world L-flavor engine template (day -> average temperature)."""

from predictionio_tpu.templates.helloworld.engine import (  # noqa: F401
    DataSourceParams,
    HelloWorldAlgorithm,
    HelloWorldDataSource,
    HelloWorldModel,
    Query,
    PredictedResult,
    TrainingData,
    engine_factory,
)
