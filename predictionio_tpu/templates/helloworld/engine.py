"""Hello-world engine: the canonical L-flavor (local) template.

Capability parity with the reference's
``examples/experimental/scala-local-helloworld/HelloWorld.scala``:

- ``MyDataSource extends LDataSource`` reads a ``day,temperature`` CSV
  on the HOST (no device mesh involved — the whole point of the L
  flavor, ``LDataSource.scala:37-71``)
- ``MyAlgorithm extends LAlgorithm`` computes the average temperature
  per day; the model is a plain host dict
- ``predict`` looks the queried day up in the model
- wired through ``SimpleEngine`` (one datasource + one algorithm,
  identity preparator, first-serving — ``EngineParams.scala:127-147``)

This is the template that exercises LDataSource/LAlgorithm through the
full train -> persist -> deploy -> query lifecycle (the reference runs
it with ``pio train``/``deploy`` like any parallel engine).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.controller import (
    LAlgorithm,
    LDataSource,
    Params,
    SimpleEngine,
)


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    """Path of the ``day,temperature`` CSV (the reference hard-codes
    ``../data/helloworld/data.csv``; a param keeps the template
    deployable from any directory)."""

    data_path: str = "data.csv"


@dataclasses.dataclass
class TrainingData:
    """(day, temperature) tuples (MyTrainingData)."""

    temperatures: List[Tuple[str, float]]

    def sanity_check(self) -> None:
        assert self.temperatures, (
            "temperatures cannot be empty — check the data file")


@dataclasses.dataclass(frozen=True)
class Query:
    day: str = ""


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    temperature: Optional[float]


@dataclasses.dataclass
class HelloWorldModel:
    """day -> average temperature (MyModel)."""

    temperatures: Dict[str, float]

    def __str__(self) -> str:
        return str(self.temperatures)


class HelloWorldDataSource(LDataSource):
    """MyDataSource: parse the CSV host-side (HelloWorld.scala:28-42)."""

    params_class = DataSourceParams

    def read_training(self) -> TrainingData:
        p: DataSourceParams = self.params
        rows: List[Tuple[str, float]] = []
        with open(p.data_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                day, temp = line.split(",")
                rows.append((day, float(temp)))
        return TrainingData(rows)


class HelloWorldAlgorithm(LAlgorithm):
    """MyAlgorithm: average per day (HelloWorld.scala:44-66)."""

    query_cls = Query

    def train(self, td: TrainingData) -> HelloWorldModel:
        sums: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        for day, temp in td.temperatures:
            sums[day] = sums.get(day, 0.0) + temp
            counts[day] = counts.get(day, 0) + 1
        return HelloWorldModel(
            {day: sums[day] / counts[day] for day in sums})

    def predict(self, model: HelloWorldModel,
                query: Query) -> PredictedResult:
        # the reference throws on an unknown day (HashMap.apply);
        # serving surfaces that as an error — mirror with None->explicit
        if query.day not in model.temperatures:
            raise KeyError(f"day {query.day!r} not in model")
        return PredictedResult(temperature=model.temperatures[query.day])


def engine_factory() -> SimpleEngine:
    """MyEngineFactory (HelloWorld.scala:69-79)."""
    return SimpleEngine(HelloWorldDataSource, HelloWorldAlgorithm)
