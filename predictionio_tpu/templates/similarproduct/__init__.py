"""Similar-product engine template (item-to-item on view events)."""

from predictionio_tpu.templates.similarproduct.engine import (  # noqa: F401
    ALSAlgorithm,
    ALSAlgorithmParams,
    DataSourceParams,
    EventDataSource,
    Item,
    ItemScore,
    LikeAlgorithm,
    LikeEvent,
    MultiServing,
    PredictedResult,
    Query,
    SimilarProductModel,
    TrainingData,
    ViewEvent,
    engine_factory,
    engine_factory_multi,
)
