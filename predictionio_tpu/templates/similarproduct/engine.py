"""Similar-product engine: view events -> ALS item factors -> item-to-item
cosine similarity.

Capability parity with ``examples/scala-parallel-similarproduct``:

- DataSource reads ``$set`` user/item entities and ``view`` events
  (``DataSource.scala``); items carry a ``categories`` property
- ALSAlgorithm aggregates view counts per (user, item), trains implicit
  ALS, keeps the item ("product") factors
  (``filterbyyear/src/main/scala/ALSAlgorithm.scala:36-87``)
- predict: sum of cosine similarities of the query items' factors against
  every item, filtered by candidate rules — not a query item, category
  intersection, white/black lists (``ALSAlgorithm.scala:89-135``).
  The reference's per-item ``.par`` cosine map becomes ONE [Q,R]x[M,R]
  matmul + reduction (MXU-shaped).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Engine,
    LFirstServing,
    LServing,
    P2LAlgorithm,
    Params,
    PDataSource,
    PIdentityPreparator,
)
from predictionio_tpu.core.context import ComputeContext
from predictionio_tpu.data.bimap import BiMap, StringIndexBiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.parallel.als_sharding import (
    train_als_auto as _train_als_auto,
)
from predictionio_tpu.ops.als import ALSParams, cosine_scores, pad_ratings


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str
    channel_name: Optional[str] = None
    # multi variant: also scan like/dislike events (an extra event-store
    # pass the base ALS engine never needs)
    read_like_events: bool = False


@dataclasses.dataclass(frozen=True)
class Item:
    categories: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ViewEvent:
    user: str
    item: str


@dataclasses.dataclass(frozen=True)
class LikeEvent:
    """like/dislike with time (multi variant, LikeAlgorithm.scala)."""
    user: str
    item: str
    like: bool
    t: float  # epoch seconds; latest event wins per (user, item)


@dataclasses.dataclass
class TrainingData:
    users: Dict[str, None]
    items: Dict[str, Item]
    view_events: List[ViewEvent]
    like_events: List[LikeEvent] = dataclasses.field(default_factory=list)

    def sanity_check(self) -> None:
        assert self.view_events, (
            "viewEvents in PreparedData cannot be empty. Please check if "
            "DataSource generates TrainingData correctly.")
        assert self.users, "users in PreparedData cannot be empty."
        assert self.items, "items in PreparedData cannot be empty."


@dataclasses.dataclass(frozen=True)
class Query:
    items: Tuple[str, ...] = ()
    num: int = 10
    categories: Tuple[str, ...] = ()
    white_list: Tuple[str, ...] = ()
    black_list: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...]


class EventDataSource(PDataSource):
    """$set users/items + view events (similarproduct DataSource.scala)."""

    params_class = DataSourceParams

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        p: DataSourceParams = self.params
        users = {
            uid: None
            for uid in PEventStore.aggregate_properties(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="user")
        }
        items = {
            iid: Item(categories=tuple(pm.get_opt("categories", list) or ()))
            for iid, pm in PEventStore.aggregate_properties(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="item").items()
        }
        views = [
            ViewEvent(user=e.entity_id, item=e.target_entity_id)
            for e in PEventStore.find(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="user", event_names=["view"],
                target_entity_type="item")
        ]
        likes: List[LikeEvent] = []
        if p.read_like_events:
            likes = [
                LikeEvent(user=e.entity_id, item=e.target_entity_id,
                          like=(e.event == "like"),
                          t=e.event_time.timestamp())
                for e in PEventStore.find(
                    app_name=p.app_name, channel_name=p.channel_name,
                    entity_type="user", event_names=["like", "dislike"],
                    target_entity_type="item")
            ]
        return TrainingData(users, items, views, likes)


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: Optional[int] = None


@dataclasses.dataclass
class SimilarProductModel:
    """Item factors + maps + item metadata (ALSModel analog)."""

    product_features: np.ndarray      # [M, R]
    item_map: StringIndexBiMap
    items: Dict[int, Item]            # item index -> metadata

    def sanity_check(self) -> None:
        assert np.isfinite(self.product_features).all()


def _train_item_model(ratings: Dict[Tuple[int, int], float],
                      user_map: StringIndexBiMap,
                      item_map: StringIndexBiMap,
                      item_meta: Dict[str, Item],
                      p: "ALSAlgorithmParams") -> SimilarProductModel:
    """Shared (user,item)->rating dict -> implicit ALS -> item-factor
    model tail used by ALSAlgorithm and LikeAlgorithm."""
    if not ratings:
        raise ValueError(
            "ratings cannot be empty. Please check if your events "
            "contain valid user and item ID.")
    keys = np.asarray(list(ratings), dtype=np.int64)
    vals = np.asarray(list(ratings.values()), dtype=np.float32)
    rows, cols = keys[:, 0], keys[:, 1]
    n_u, n_i = len(user_map), len(item_map)
    params = ALSParams(rank=p.rank, num_iterations=p.num_iterations,
                       lambda_=p.lambda_,
                       seed=0 if p.seed is None else p.seed)
    _, item_factors = _train_als_auto(
        pad_ratings(rows, cols, vals, n_u, n_i),
        pad_ratings(cols, rows, vals, n_i, n_u),
        params)
    items = {item_map[iid]: item for iid, item in item_meta.items()}
    return SimilarProductModel(item_factors, item_map, items)


class ALSAlgorithm(P2LAlgorithm):
    """Implicit ALS on view counts; keeps productFeatures
    (ALSAlgorithm.scala:36-87)."""

    params_class = ALSAlgorithmParams
    query_cls = Query

    def train(self, ctx: ComputeContext,
              pd: TrainingData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        user_map = BiMap.string_int(pd.users)
        item_map = BiMap.string_int(pd.items)
        # aggregate all view events of the same user-item pair
        counts: Dict[Tuple[int, int], float] = {}
        for v in pd.view_events:
            u, i = user_map.get(v.user), item_map.get(v.item)
            if u is None or i is None:
                continue  # view of an entity without a $set (scala :59-66)
            counts[(u, i)] = counts.get((u, i), 0.0) + 1.0
        return _train_item_model(counts, user_map, item_map, pd.items, p)

    def predict(self, model: SimilarProductModel,
                query: Query) -> PredictedResult:
        idxs = [model.item_map[i] for i in query.items
                if i in model.item_map]
        if not idxs:
            return PredictedResult(())
        qf = model.product_features[np.asarray(idxs, dtype=np.int64)]
        # [Q, M] cosines summed over query items (scala :101-110)
        scores = cosine_scores(qf, model.product_features)
        scores = np.where(np.isfinite(scores), scores, 0.0)

        mask = scores > 0  # keep positive-score items (scala :109)
        mask[np.asarray(idxs, dtype=np.int64)] = False  # not the query items
        if query.categories:
            cats = set(query.categories)
            for ix, item in model.items.items():
                if not cats.intersection(item.categories):
                    mask[ix] = False
        if query.white_list:
            white = {model.item_map[i] for i in query.white_list
                     if i in model.item_map}
            keep = np.zeros_like(mask)
            if white:
                keep[np.asarray(list(white), dtype=np.int64)] = True
            mask &= keep
        for i in query.black_list:
            ix = model.item_map.get(i)
            if ix is not None:
                mask[ix] = False

        scores = np.where(mask, scores, -np.inf)
        k = min(query.num, int(mask.sum()))
        if k <= 0:
            return PredictedResult(())
        top = np.argpartition(-scores, k - 1)[:k]
        top = top[np.argsort(-scores[top])]
        items = model.item_map.decode(top)
        return PredictedResult(tuple(
            ItemScore(item=str(i), score=float(scores[ix]))
            for i, ix in zip(items, top)))


class LikeAlgorithm(ALSAlgorithm):
    """multi variant: ALS on like/dislike events — an user may flip
    opinion, so the LATEST event per (user, item) wins; like -> +1,
    dislike -> -1, trained with implicit confidence (negative value =
    negative signal). Mirrors ``multi/.../LikeAlgorithm.scala:21-102``."""

    def train(self, ctx: ComputeContext,
              pd: TrainingData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        if not pd.like_events:
            raise ValueError(
                "likeEvents in PreparedData cannot be empty. Please check "
                "if DataSource generates TrainingData correctly.")
        user_map = BiMap.string_int(pd.users)
        item_map = BiMap.string_int(pd.items)
        latest: Dict[Tuple[int, int], Tuple[bool, float]] = {}
        for ev in pd.like_events:
            u, i = user_map.get(ev.user), item_map.get(ev.item)
            if u is None or i is None:
                continue
            prev = latest.get((u, i))
            if prev is None or ev.t > prev[1]:
                latest[(u, i)] = (ev.like, ev.t)
        ratings = {k: (1.0 if like else -1.0)
                   for k, (like, _) in latest.items()}
        return _train_item_model(ratings, user_map, item_map, pd.items, p)


class MultiServing(LServing):
    """multi variant Serving: z-score standardize each algorithm's scores
    (skipped for num==1), then sum per item and take top num
    (``multi/.../Serving.scala:16-52``)."""

    def serve(self, query: Query,
              predictions: List[PredictedResult]) -> PredictedResult:
        if query.num == 1:
            standardized = [pr.item_scores for pr in predictions]
        else:
            standardized = []
            for pr in predictions:
                scores = np.asarray([s.score for s in pr.item_scores],
                                    dtype=np.float64)
                if len(scores) and scores.std() > 0:
                    z = (scores - scores.mean()) / scores.std()
                else:
                    z = np.zeros_like(scores)
                standardized.append(tuple(
                    ItemScore(s.item, float(zs))
                    for s, zs in zip(pr.item_scores, z)))
        combined: Dict[str, float] = {}
        for group in standardized:
            for s in group:
                combined[s.item] = combined.get(s.item, 0.0) + s.score
        ranked = sorted(combined.items(), key=lambda kv: -kv[1])
        return PredictedResult(tuple(
            ItemScore(item=k, score=v)
            for k, v in ranked[:query.num]))


def engine_factory() -> Engine:
    """SimilarProductEngine (similarproduct Engine.scala)."""
    return Engine(
        EventDataSource,
        PIdentityPreparator,
        {"als": ALSAlgorithm, "": ALSAlgorithm},
        LFirstServing,
    )


def engine_factory_multi() -> Engine:
    """multi variant: ALS + LikeAlgorithm ensemble combined by z-score
    serving (``multi/.../Engine.scala:29-33``)."""
    return Engine(
        EventDataSource,
        PIdentityPreparator,
        {"als": ALSAlgorithm, "likealgo": LikeAlgorithm},
        MultiServing,
    )
