"""Similar-product engine: view events -> ALS item factors -> item-to-item
cosine similarity.

Capability parity with ``examples/scala-parallel-similarproduct``:

- DataSource reads ``$set`` user/item entities and ``view`` events
  (``DataSource.scala``); items carry a ``categories`` property
- ALSAlgorithm aggregates view counts per (user, item), trains implicit
  ALS, keeps the item ("product") factors
  (``filterbyyear/src/main/scala/ALSAlgorithm.scala:36-87``)
- predict: sum of cosine similarities of the query items' factors against
  every item, filtered by candidate rules — not a query item, category
  intersection, white/black lists (``ALSAlgorithm.scala:89-135``).
  The reference's per-item ``.par`` cosine map becomes ONE [Q,R]x[M,R]
  matmul + reduction (MXU-shaped).
- filterbyyear variant: items carry a ``year`` property and queries a
  ``recommendFromYear`` floor; candidates must satisfy
  ``year > recommendFromYear`` and results carry the year
  (``filterbyyear/src/main/scala/ALSAlgorithm.scala:225-240``,
  ``Engine.scala:10-23``)
- recommended-user variant: ALS on ``follow`` events (user -> user),
  user-to-user cosine recommendations with white/black lists
  (``recommended-user/src/main/scala/ALSAlgorithm.scala:44-168``)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Engine,
    LFirstServing,
    LServing,
    P2LAlgorithm,
    Params,
    PDataSource,
    PIdentityPreparator,
)
from predictionio_tpu.core.context import ComputeContext
from predictionio_tpu.data.bimap import BiMap, StringIndexBiMap
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.parallel.als_sharding import (
    train_als_auto as _train_als_auto,
)
from predictionio_tpu.ops.als import ALSParams, cosine_scores, pad_ratings


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str
    channel_name: Optional[str] = None
    # multi variant: also scan like/dislike events (an extra event-store
    # pass the base ALS engine never needs)
    read_like_events: bool = False
    # no-set-user variant: users come from the view events themselves —
    # no $set user entities required
    # (no-set-user/src/main/scala/ALSAlgorithm.scala:58: BiMap over
    # viewEvents.map(_.user))
    no_set_user: bool = False
    # add-and-return-item-properties variant: capture title/date/imdbUrl
    # into the model so the algorithm's return_item_properties flag can
    # serve them; off by default so base-flavor model blobs don't carry
    # strings they never serve
    read_item_properties: bool = False


@dataclasses.dataclass(frozen=True)
class Item:
    categories: Tuple[str, ...] = ()
    # filterbyyear variant (DataSource.scala:52/:100 there requires it;
    # merged template keeps it optional so the base flavor is unchanged)
    year: Optional[int] = None
    # add-and-return-item-properties variant
    # (add-and-return-item-properties/.../DataSource.scala:53-55)
    title: str = ""
    date: str = ""
    imdb_url: str = ""


@dataclasses.dataclass(frozen=True)
class ViewEvent:
    user: str
    item: str


@dataclasses.dataclass(frozen=True)
class LikeEvent:
    """like/dislike with time (multi variant, LikeAlgorithm.scala)."""
    user: str
    item: str
    like: bool
    t: float  # epoch seconds; latest event wins per (user, item)


@dataclasses.dataclass
class TrainingData:
    users: Dict[str, None]
    items: Dict[str, Item]
    view_events: List[ViewEvent]
    like_events: List[LikeEvent] = dataclasses.field(default_factory=list)
    # True when the DataSource captured title/date/imdbUrl (the
    # add-and-return-item-properties prerequisite)
    item_properties_read: bool = False

    def sanity_check(self) -> None:
        assert self.view_events, (
            "viewEvents in PreparedData cannot be empty. Please check if "
            "DataSource generates TrainingData correctly.")
        assert self.users, "users in PreparedData cannot be empty."
        assert self.items, "items in PreparedData cannot be empty."


@dataclasses.dataclass(frozen=True)
class Query:
    items: Tuple[str, ...] = ()
    num: int = 10
    categories: Tuple[str, ...] = ()
    white_list: Tuple[str, ...] = ()
    black_list: Tuple[str, ...] = ()
    # filterbyyear variant: only items with year > this floor recommend
    # (filterbyyear Engine.scala:12, ALSAlgorithm.scala:231)
    recommend_from_year: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class YearItemScore:
    """filterbyyear's ItemScore shape (its Engine.scala:19-23 adds the
    year). A distinct type so the BASE flavor's wire format stays
    byte-identical to the reference base template (no `year` key)."""

    item: str
    score: float
    year: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class RichItemScore:
    """add-and-return-item-properties' ItemScore shape (its
    Engine.scala:18-24): results carry the stored item properties."""

    item: str
    title: str
    date: str
    imdb_url: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...]


class EventDataSource(PDataSource):
    """$set users/items + view events (similarproduct DataSource.scala).
    With ``no_set_user`` the user set is derived from the view events
    instead of $set entities (no-set-user variant)."""

    params_class = DataSourceParams

    def read_training(self, ctx: ComputeContext) -> TrainingData:
        p: DataSourceParams = self.params
        def to_item(pm) -> Item:
            kw = {"categories": tuple(pm.get_opt("categories", list) or ()),
                  "year": pm.get_opt("year", int)}
            if p.read_item_properties:
                kw.update(title=pm.get_opt("title", str) or "",
                          date=pm.get_opt("date", str) or "",
                          imdb_url=pm.get_opt("imdbUrl", str) or "")
            return Item(**kw)

        items = {
            iid: to_item(pm)
            for iid, pm in PEventStore.aggregate_properties(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="item").items()
        }
        views = [
            ViewEvent(user=e.entity_id, item=e.target_entity_id)
            for e in PEventStore.find(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="user", event_names=["view"],
                target_entity_type="item")
        ]
        if p.no_set_user:
            # users are whoever viewed (no-set-user ALSAlgorithm.scala:58)
            users = {v.user: None for v in views}
        else:
            users = {
                uid: None
                for uid in PEventStore.aggregate_properties(
                    app_name=p.app_name, channel_name=p.channel_name,
                    entity_type="user")
            }
        likes: List[LikeEvent] = []
        if p.read_like_events:
            likes = [
                LikeEvent(user=e.entity_id, item=e.target_entity_id,
                          like=(e.event == "like"),
                          t=e.event_time.timestamp())
                for e in PEventStore.find(
                    app_name=p.app_name, channel_name=p.channel_name,
                    entity_type="user", event_names=["like", "dislike"],
                    target_entity_type="item")
            ]
        return TrainingData(users, items, views, likes,
                            item_properties_read=p.read_item_properties)


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: Optional[int] = None
    # add-and-return-item-properties variant: results carry the stored
    # item title/date/imdbUrl (RichItemScore). Ignored when a query's
    # recommend_from_year is set (that filter returns YearItemScore).
    return_item_properties: bool = False


@dataclasses.dataclass
class SimilarProductModel:
    """Item factors + maps + item metadata (ALSModel analog)."""

    product_features: np.ndarray      # [M, R]
    item_map: StringIndexBiMap
    items: Dict[int, Item]            # item index -> metadata

    def sanity_check(self) -> None:
        assert np.isfinite(self.product_features).all()


def _factors_from_ratings(ratings: Dict[Tuple[int, int], float],
                          n_rows: int, n_cols: int,
                          p: "ALSAlgorithmParams",
                          empty_msg: str) -> Tuple[np.ndarray, np.ndarray]:
    """(row,col)->value dict -> implicit ALS factor pair; the tail every
    flavor in this module shares."""
    if not ratings:
        raise ValueError(empty_msg)
    keys = np.asarray(list(ratings), dtype=np.int64)
    vals = np.asarray(list(ratings.values()), dtype=np.float32)
    params = ALSParams(rank=p.rank, num_iterations=p.num_iterations,
                       lambda_=p.lambda_,
                       seed=0 if p.seed is None else p.seed)
    return _train_als_auto(
        pad_ratings(keys[:, 0], keys[:, 1], vals, n_rows, n_cols),
        pad_ratings(keys[:, 1], keys[:, 0], vals, n_cols, n_rows),
        params)


def _train_item_model(ratings: Dict[Tuple[int, int], float],
                      user_map: StringIndexBiMap,
                      item_map: StringIndexBiMap,
                      item_meta: Dict[str, Item],
                      p: "ALSAlgorithmParams") -> SimilarProductModel:
    """Shared (user,item)->rating dict -> implicit ALS -> item-factor
    model tail used by ALSAlgorithm and LikeAlgorithm."""
    _, item_factors = _factors_from_ratings(
        ratings, len(user_map), len(item_map), p,
        "ratings cannot be empty. Please check if your events "
        "contain valid user and item ID.")
    items = {item_map[iid]: item for iid, item in item_meta.items()}
    return SimilarProductModel(item_factors, item_map, items)


def _filter_topk(scores: np.ndarray, idxs: List[int], num: int,
                 id_map: StringIndexBiMap,
                 white_list: Tuple[str, ...],
                 black_list: Tuple[str, ...],
                 extra_mask: Optional[np.ndarray] = None
                 ) -> List[Tuple[str, float, int]]:
    """The candidate-filter + top-k shared by every score-serving flavor
    (isCandidateItem / isCandidateSimilarUser in the reference variants):
    keep positive scores, drop the query rows themselves, apply
    white/black lists (and any variant-specific ``extra_mask``), return
    ``(decoded id, score, row index)`` descending."""
    scores = np.where(np.isfinite(scores), scores, 0.0)
    mask = scores > 0
    mask[np.asarray(idxs, dtype=np.int64)] = False
    if extra_mask is not None:
        mask &= extra_mask
    if white_list:
        white = {id_map[i] for i in white_list if i in id_map}
        keep = np.zeros_like(mask)
        if white:
            keep[np.asarray(list(white), dtype=np.int64)] = True
        mask &= keep
    for i in black_list:
        ix = id_map.get(i)
        if ix is not None:
            mask[ix] = False
    scores = np.where(mask, scores, -np.inf)
    k = min(num, int(mask.sum()))
    if k <= 0:
        return []
    top = np.argpartition(-scores, k - 1)[:k]
    top = top[np.argsort(-scores[top])]
    decoded = id_map.decode(top)
    return [(str(d), float(scores[ix]), int(ix))
            for d, ix in zip(decoded, top)]


def _category_mask(items: Dict[int, Item], n: int,
                   categories: Tuple[str, ...]) -> np.ndarray:
    """Candidate mask for the category-intersection rule shared by every
    similarproduct flavor (isCandidateItem's categories clause): items
    without an overlapping category — or without metadata — are out."""
    mask = np.zeros(n, dtype=bool)
    cats = set(categories)
    for ix, item in items.items():
        if cats.intersection(item.categories):
            mask[ix] = True
    return mask


def _cosine_topk(features: np.ndarray, idxs: List[int], num: int,
                 id_map: StringIndexBiMap,
                 white_list: Tuple[str, ...],
                 black_list: Tuple[str, ...],
                 extra_mask: Optional[np.ndarray] = None
                 ) -> List[Tuple[str, float, int]]:
    """Summed cosine scores of the query rows against all rows, then the
    shared candidate filter + top-k."""
    qf = features[np.asarray(idxs, dtype=np.int64)]
    scores = cosine_scores(qf, features)
    return _filter_topk(scores, idxs, num, id_map, white_list, black_list,
                        extra_mask)


class ALSAlgorithm(P2LAlgorithm):
    """Implicit ALS on view counts; keeps productFeatures
    (ALSAlgorithm.scala:36-87)."""

    params_class = ALSAlgorithmParams
    query_cls = Query

    def train(self, ctx: ComputeContext,
              pd: TrainingData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        user_map = BiMap.string_int(pd.users)
        item_map = BiMap.string_int(pd.items)
        # aggregate all view events of the same user-item pair
        counts: Dict[Tuple[int, int], float] = {}
        for v in pd.view_events:
            u, i = user_map.get(v.user), item_map.get(v.item)
            if u is None or i is None:
                continue  # view of an entity without a $set (scala :59-66)
            counts[(u, i)] = counts.get((u, i), 0.0) + 1.0
        if getattr(p, "return_item_properties", False) \
                and not getattr(pd, "item_properties_read", False):
            # a mismatched flag pair would silently serve empty strings
            raise ValueError(
                "return_item_properties=True requires "
                "DataSourceParams(read_item_properties=True) so the "
                "title/date/imdbUrl properties are captured into the "
                "model")
        return _train_item_model(counts, user_map, item_map, pd.items, p)

    def predict(self, model: SimilarProductModel,
                query: Query) -> PredictedResult:
        idxs = [model.item_map[i] for i in query.items
                if i in model.item_map]
        if not idxs:
            return PredictedResult(())
        extra = None
        year_filter = query.recommend_from_year is not None
        if query.categories or year_filter:
            n = model.product_features.shape[0]
            extra = (_category_mask(model.items, n, query.categories)
                     if query.categories else np.ones(n, dtype=bool))
            if year_filter:
                # year floor (filterbyyear ALSAlgorithm.scala:231): items
                # without a year never recommend under this filter,
                # matching the variant's required `year` property. Old
                # pickled models may predate the field -> getattr.
                for ix, item in model.items.items():
                    year = getattr(item, "year", None)
                    if year is None or year <= query.recommend_from_year:
                        extra[ix] = False
        winners = _cosine_topk(model.product_features, idxs, query.num,
                               model.item_map, query.white_list,
                               query.black_list, extra)
        if year_filter:
            # the filterbyyear variant's results carry the item year
            # (its Engine.scala:19-23); the base flavor's wire format
            # stays untouched
            return PredictedResult(tuple(
                YearItemScore(item=item, score=score,
                              year=getattr(model.items.get(ix, Item()),
                                           "year", None))
                for item, score, ix in winners))
        if getattr(self.params, "return_item_properties", False):
            # add-and-return-item-properties variant (its
            # Engine.scala:18-24); getattr guards old pickled Items
            def rich(item, score, ix):
                meta = model.items.get(ix, Item())
                return RichItemScore(
                    item=item, score=score,
                    title=getattr(meta, "title", ""),
                    date=getattr(meta, "date", ""),
                    imdb_url=getattr(meta, "imdb_url", ""))
            return PredictedResult(tuple(
                rich(*w) for w in winners))
        return PredictedResult(tuple(
            ItemScore(item=item, score=score)
            for item, score, _ in winners))


class LikeAlgorithm(ALSAlgorithm):
    """multi variant: ALS on like/dislike events — an user may flip
    opinion, so the LATEST event per (user, item) wins; like -> +1,
    dislike -> -1, trained with implicit confidence (negative value =
    negative signal). Mirrors ``multi/.../LikeAlgorithm.scala:21-102``."""

    def train(self, ctx: ComputeContext,
              pd: TrainingData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        if not pd.like_events:
            raise ValueError(
                "likeEvents in PreparedData cannot be empty. Please check "
                "if DataSource generates TrainingData correctly.")
        user_map = BiMap.string_int(pd.users)
        item_map = BiMap.string_int(pd.items)
        latest: Dict[Tuple[int, int], Tuple[bool, float]] = {}
        for ev in pd.like_events:
            u, i = user_map.get(ev.user), item_map.get(ev.item)
            if u is None or i is None:
                continue
            prev = latest.get((u, i))
            if prev is None or ev.t > prev[1]:
                latest[(u, i)] = (ev.like, ev.t)
        ratings = {k: (1.0 if like else -1.0)
                   for k, (like, _) in latest.items()}
        return _train_item_model(ratings, user_map, item_map, pd.items, p)


@dataclasses.dataclass(frozen=True)
class DIMSUMAlgorithmParams(Params):
    """DIMSUMAlgorithmParams (experimental similarproduct-dimsum,
    ``DIMSUMAlgorithm.scala:23``): similarities below ``threshold`` are
    dropped. Spark's columnSimilarities(threshold) SAMPLES to
    approximate high-similarity pairs cheaply; one device matmul
    computes them exactly here, so the threshold is an exact cut."""

    threshold: float = 0.0


@dataclasses.dataclass
class DIMSUMModel:
    """Item-item cosine similarity matrix + maps + item metadata
    (DIMSUMModel, ``DIMSUMAlgorithm.scala:25-52`` — the RDD of sparse
    similarity vectors becomes one dense [M, M] float32 table; item
    vocabularies at this template's scale fit comfortably)."""

    similarities: np.ndarray          # [M, M] float32, zero diagonal
    item_map: StringIndexBiMap
    items: Dict[int, Item]

    def sanity_check(self) -> None:
        assert np.isfinite(self.similarities).all()


class DIMSUMAlgorithm(P2LAlgorithm):
    """Item-to-item cosine similarity computed DIRECTLY from the binary
    user x item view matrix — no factorization
    (``DIMSUMAlgorithm.scala:72-140``: RowMatrix.columnSimilarities).
    TPU-native: column-normalize the interaction matrix and take one
    A^T A matmul on the MXU instead of Spark's sampled shuffle."""

    params_class = DIMSUMAlgorithmParams
    query_cls = Query

    def train(self, ctx: ComputeContext,
              pd: TrainingData) -> DIMSUMModel:
        import jax
        import jax.numpy as jnp

        p: DIMSUMAlgorithmParams = self.params
        user_map = BiMap.string_int(pd.users)
        item_map = BiMap.string_int(pd.items)
        n_u, n_i = len(user_map), len(item_map)
        # binary de-duplicated (user, item) matrix ("keep one copy",
        # DIMSUMAlgorithm.scala:104-115)
        pairs = {(user_map[v.user], item_map[v.item])
                 for v in pd.view_events
                 if v.user in user_map and v.item in item_map}
        if not pairs:
            raise ValueError(
                "viewEvents produced no valid (user, item) pairs. Please "
                "check if your events contain valid user and item ID.")
        A = np.zeros((n_u, n_i), dtype=np.float32)
        keys = np.asarray(list(pairs), dtype=np.int64)
        A[keys[:, 0], keys[:, 1]] = 1.0

        @jax.jit
        def column_similarities(A):
            norms = jnp.maximum(jnp.linalg.norm(A, axis=0), 1e-12)
            An = A / norms[None, :]
            S = jnp.matmul(An.T, An,
                           precision=jax.lax.Precision.HIGHEST)
            S = S * (1.0 - jnp.eye(S.shape[0], dtype=S.dtype))
            return jnp.where(S >= p.threshold, S, 0.0)

        sims = np.asarray(column_similarities(jnp.asarray(A)))
        items = {item_map[iid]: item for iid, item in pd.items.items()}
        return DIMSUMModel(sims, item_map, items)

    def predict(self, model: DIMSUMModel, query: Query) -> PredictedResult:
        idxs = [model.item_map[i] for i in query.items
                if i in model.item_map]
        if not idxs:
            return PredictedResult(())
        # sum the query items' similarity rows (DIMSUMAlgorithm.scala:
        # 153-180 flatMap + groupBy-sum), then the shared filters
        scores = model.similarities[np.asarray(idxs, dtype=np.int64)] \
            .sum(axis=0)
        extra = (_category_mask(model.items, len(scores),
                                query.categories)
                 if query.categories else None)
        winners = _filter_topk(scores, idxs, query.num, model.item_map,
                               query.white_list, query.black_list, extra)
        return PredictedResult(tuple(
            ItemScore(item=item, score=score)
            for item, score, _ in winners))


class MultiServing(LServing):
    """multi variant Serving: z-score standardize each algorithm's scores
    (skipped for num==1), then sum per item and take top num
    (``multi/.../Serving.scala:16-52``)."""

    def serve(self, query: Query,
              predictions: List[PredictedResult]) -> PredictedResult:
        if query.num == 1:
            standardized = [pr.item_scores for pr in predictions]
        else:
            standardized = []
            for pr in predictions:
                scores = np.asarray([s.score for s in pr.item_scores],
                                    dtype=np.float64)
                if len(scores) and scores.std() > 0:
                    z = (scores - scores.mean()) / scores.std()
                else:
                    z = np.zeros_like(scores)
                standardized.append(tuple(
                    ItemScore(s.item, float(zs))
                    for s, zs in zip(pr.item_scores, z)))
        combined: Dict[str, float] = {}
        for group in standardized:
            for s in group:
                combined[s.item] = combined.get(s.item, 0.0) + s.score
        ranked = sorted(combined.items(), key=lambda kv: -kv[1])
        return PredictedResult(tuple(
            ItemScore(item=k, score=v)
            for k, v in ranked[:query.num]))


# ---------------------------------------------------------------------------
# recommended-user variant: who to follow
# (examples/scala-parallel-similarproduct/recommended-user/)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class UserQuery:
    """recommended-user Engine.scala:6-13: query by user IDs."""

    users: Tuple[str, ...] = ()
    num: int = 10
    white_list: Tuple[str, ...] = ()
    black_list: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class SimilarUserScore:
    user: str
    score: float


@dataclasses.dataclass(frozen=True)
class RecommendedUsersResult:
    similar_user_scores: Tuple[SimilarUserScore, ...]


@dataclasses.dataclass
class FollowTrainingData:
    users: Dict[str, None]
    follow_events: List[ViewEvent]  # user -> followed user (reuses shape)

    def sanity_check(self) -> None:
        assert self.follow_events, (
            "followEvents in PreparedData cannot be empty. Please check "
            "if DataSource generates TrainingData correctly.")
        assert self.users, "users in PreparedData cannot be empty."


class FollowDataSource(PDataSource):
    """$set users + follow events (recommended-user DataSource.scala:
    user -> followedUser, both entity types 'user')."""

    params_class = DataSourceParams

    def read_training(self, ctx: ComputeContext) -> FollowTrainingData:
        p: DataSourceParams = self.params
        users = {
            uid: None
            for uid in PEventStore.aggregate_properties(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="user")
        }
        follows = [
            ViewEvent(user=e.entity_id, item=e.target_entity_id)
            for e in PEventStore.find(
                app_name=p.app_name, channel_name=p.channel_name,
                entity_type="user", event_names=["follow"],
                target_entity_type="user")
        ]
        return FollowTrainingData(users, follows)


@dataclasses.dataclass
class RecommendedUserModel:
    """similarUserFeatures + one shared user map
    (recommended-user ALSAlgorithm.scala:18-34)."""

    similar_user_features: np.ndarray  # [N, R]
    user_map: StringIndexBiMap

    def sanity_check(self) -> None:
        assert np.isfinite(self.similar_user_features).all()


class RecommendedUserAlgorithm(P2LAlgorithm):
    """Implicit ALS on follow counts over one user x user matrix; the
    'product' factors are the followed-user features served by cosine
    (recommended-user ALSAlgorithm.scala:44-168)."""

    params_class = ALSAlgorithmParams
    query_cls = UserQuery

    def train(self, ctx: ComputeContext,
              pd: FollowTrainingData) -> RecommendedUserModel:
        p: ALSAlgorithmParams = self.params
        user_map = BiMap.string_int(pd.users)
        counts: Dict[Tuple[int, int], float] = {}
        for f in pd.follow_events:
            u, v = user_map.get(f.user), user_map.get(f.item)
            if u is None or v is None:
                continue  # follow of an un-$set user (scala :66-80)
            counts[(u, v)] = counts.get((u, v), 0.0) + 1.0
        n = len(user_map)
        _, followed_factors = _factors_from_ratings(
            counts, n, n, p,
            "mllibRatings cannot be empty. Please check if your "
            "events contain valid user and followedUser ID.")
        return RecommendedUserModel(followed_factors, user_map)

    def predict(self, model: RecommendedUserModel,
                query: UserQuery) -> RecommendedUsersResult:
        idxs = [model.user_map[u] for u in query.users
                if u in model.user_map]
        if not idxs:
            return RecommendedUsersResult(())
        winners = _cosine_topk(model.similar_user_features, idxs,
                               query.num, model.user_map,
                               query.white_list, query.black_list)
        return RecommendedUsersResult(tuple(
            SimilarUserScore(user=user, score=score)
            for user, score, _ in winners))


def engine_factory() -> Engine:
    """SimilarProductEngine (similarproduct Engine.scala)."""
    return Engine(
        EventDataSource,
        PIdentityPreparator,
        {"als": ALSAlgorithm, "": ALSAlgorithm},
        LFirstServing,
    )


def engine_factory_recommended_user() -> Engine:
    """RecommendedUserEngine (recommended-user Engine.scala:22-30)."""
    return Engine(
        FollowDataSource,
        PIdentityPreparator,
        {"als": RecommendedUserAlgorithm, "": RecommendedUserAlgorithm},
        LFirstServing,
    )


def engine_factory_dimsum() -> Engine:
    """DIMSUM variant: similarities from the raw interaction matrix
    instead of factors (experimental scala-parallel-similarproduct-dimsum
    Engine.scala)."""
    return Engine(
        EventDataSource,
        PIdentityPreparator,
        {"dimsum": DIMSUMAlgorithm, "": DIMSUMAlgorithm},
        LFirstServing,
    )


def engine_factory_multi() -> Engine:
    """multi variant: ALS + LikeAlgorithm ensemble combined by z-score
    serving (``multi/.../Engine.scala:29-33``)."""
    return Engine(
        EventDataSource,
        PIdentityPreparator,
        {"als": ALSAlgorithm, "likealgo": LikeAlgorithm},
        MultiServing,
    )
